#
# Efficiency attribution plane: per-tenant device-time accounting, the
# compile ledger, and roofline/MFU gauges (docs/observability.md
# "Efficiency plane").
#
# PR 13's ledger answers "who held how many bytes for how long"; nothing
# answered "what were the chips DOING during those seconds" — a chip-second
# spent 95%-idle in a host-sync stall was billed identically to one
# saturating the MXU. This module splits attributed wall time into four
# kinds per tenant:
#
#   execute  — measured `block_until_ready` waits at boundaries that ALREADY
#              host-fetch (solver cadence points, `run_segmented_while`
#              segments, streaming chunk partials, serving response
#              assembly). A LOWER bound on device-busy time: compute that
#              overlapped host work before the wait is not seen here.
#   compile  — first-sighting walls from the compile ledger (below). An
#              UPPER bound: a miss wall includes the first execution.
#   host     — measured host-side sections at the same boundaries
#              (checkpoint serialization, response slicing).
#   idle     — the residual: scope wall minus the three measured kinds,
#              clamped at zero. Unattributed python/dispatch overhead lands
#              here, which is exactly the on-call question ("where did the
#              wall go that no stage accounts for").
#
# By construction execute + compile + host + idle == wall for every scope,
# so the roll-up attributes 100% of fit wall time to named kinds; per-stage
# idle is the scope idle distributed proportionally to each stage's
# pre-boundary gap (the window in which the device may have starved).
#
# Contracts:
#   * zero-cost when telemetry is disabled: `attribution_scope` returns a
#     shared no-op, and the telemetry.py hooks (`device_wait`,
#     `host_section`, `compile_event`) bail on one `_STATE.on` check before
#     this module is even imported. No extra syncs, ever: every timer wraps
#     a fetch the caller already performed.
#   * the compile ledger is ALWAYS process-wide (prewarm runs outside any
#     fit scope); scope attribution is layered on top when a scope is
#     active on the calling thread.
#   * nested timers never double-count: the outermost attribution wins
#     (a compile miss wrapping a solve swallows the solve's inner waits).
#
from __future__ import annotations

import contextvars
import time
from typing import Any, Dict, Optional, Tuple

from ..utils import lockcheck

__all__ = [
    "attribution_scope",
    "active",
    "compile_event",
    "compile_stats",
    "note_flops",
    "peak_flops",
    "summary",
    "tenant_time_splits",
    "reset",
]

_KINDS = ("execute_s", "compile_s", "host_s", "idle_s")

_LOCK = lockcheck.make_lock("ops_plane.efficiency._LOCK")
# tenant -> {execute_s, compile_s, host_s, idle_s, wall_s, scopes}  # guarded-by: _LOCK
_TENANTS: Dict[str, Dict[str, float]] = {}
# tenant -> stage -> {execute_s, host_s, idle_s, events}  # guarded-by: _LOCK
_STAGES: Dict[str, Dict[str, Dict[str, float]]] = {}

_COMPILE_LOCK = lockcheck.make_lock("ops_plane.efficiency._COMPILE_LOCK")
# (program, shape_key) -> {misses, hits, wall_s}  # guarded-by: _COMPILE_LOCK
_COMPILE: Dict[Tuple[str, str], Dict[str, float]] = {}

_SCOPE: "contextvars.ContextVar[Optional[_Scope]]" = contextvars.ContextVar(
    "srml_efficiency_scope", default=None
)


def _registry():
    from .. import telemetry

    return telemetry.registry() if telemetry.enabled() else None


# ------------------------------------------------------------ peak spec ----


def parse_peak_spec(spec: Any) -> Optional[float]:
    """Peak-spec grammar (docs/observability.md "Efficiency plane"): a
    number with an optional K/M/G/T/P suffix — ``"14T"``, ``"275e12"``,
    ``900e9`` — in FLOP/s per device. None/empty/unparseable = no peak
    (gauges omitted, never guessed)."""
    if spec is None:
        return None
    if isinstance(spec, (int, float)):
        return float(spec) if spec > 0 else None
    s = str(spec).strip()
    if not s:
        return None
    mult = 1.0
    suffix = {"k": 1e3, "m": 1e6, "g": 1e9, "t": 1e12, "p": 1e15}
    if s[-1].lower() in suffix:
        mult = suffix[s[-1].lower()]
        s = s[:-1]
    try:
        v = float(s) * mult
    except ValueError:
        return None
    return v if v > 0 else None


def peak_flops() -> Optional[float]:
    """The configured per-device peak (`config["device_peak_flops"]`,
    seeded from `SRML_DEVICE_PEAK_FLOPS`), parsed; None when unset."""
    try:
        from ..core import config
    except Exception:
        return None
    return parse_peak_spec(config.get("device_peak_flops"))


# ------------------------------------------------------- attribution scope --


class _Scope:
    """One attribution window (a fit, or one serving dispatch group):
    accumulates measured seconds by (kind, stage) on the opening thread,
    then folds into the per-tenant module totals at close."""

    __slots__ = (
        "label", "tenant", "trace_id", "t0", "mark", "depth",
        "kinds", "stages", "flops", "chips", "compile_hits",
        "compile_misses", "closed", "_token",
    )

    def __init__(self, label: str, tenant: str, trace_id: Optional[str]):
        self.label = label
        self.tenant = tenant
        self.trace_id = trace_id
        self.t0 = time.perf_counter()
        self.mark = self.t0  # last boundary exit (gap accounting)
        self.depth = 0  # >0 while an attribution timer is open
        self.kinds = {"execute_s": 0.0, "compile_s": 0.0, "host_s": 0.0}
        # stage -> {execute_s, host_s, gap_s, events}
        self.stages: Dict[str, Dict[str, float]] = {}
        self.flops = 0.0
        self.chips = 1
        self.compile_hits = 0
        self.compile_misses = 0
        self.closed = False
        self._token = None

    # -- accumulation (single-threaded: the scope's opening thread) --------
    def _stage(self, stage: str) -> Dict[str, float]:
        st = self.stages.get(stage)
        if st is None:
            st = self.stages[stage] = {
                "execute_s": 0.0, "host_s": 0.0, "gap_s": 0.0, "events": 0.0,
            }
        return st

    def note(self, kind: str, stage: str, seconds: float, gap: float) -> None:
        # kind is "execute_s" or "host_s" (compile attributes directly from
        # the ledger event, which has no stage of its own)
        self.kinds[kind] += seconds
        st = self._stage(stage)
        st[kind] += seconds
        st["gap_s"] += gap
        st["events"] += 1

    # -- close -------------------------------------------------------------
    def summary_dict(self) -> Dict[str, Any]:
        wall = max(0.0, time.perf_counter() - self.t0)
        accounted = sum(self.kinds.values())
        idle = max(0.0, wall - accounted)
        total_gap = sum(st["gap_s"] for st in self.stages.values())
        stages: Dict[str, Dict[str, float]] = {}
        top_idle, top_idle_s = None, -1.0
        for name, st in self.stages.items():
            stage_idle = idle * (st["gap_s"] / total_gap) if total_gap > 0 else 0.0
            stages[name] = {
                "execute_s": st["execute_s"],
                "host_s": st["host_s"],
                "idle_s": stage_idle,
                "events": int(st["events"]),
            }
            if stage_idle > top_idle_s:
                top_idle, top_idle_s = name, stage_idle
        out: Dict[str, Any] = {
            "wall_s": wall,
            "execute_s": self.kinds["execute_s"],
            "compile_s": self.kinds["compile_s"],
            "host_s": self.kinds["host_s"],
            "idle_s": idle,
            "stages": stages,
            "top_idle_stage": top_idle,
            "compile": {"hits": self.compile_hits, "misses": self.compile_misses},
        }
        peak = peak_flops()
        if peak is not None and self.flops > 0 and wall > 0:
            out["mfu"] = self.flops / (wall * peak * max(1, self.chips))
            out["flops"] = self.flops
        return out

    def close(self) -> Dict[str, Any]:
        if self.closed:
            return {}
        self.closed = True
        out = self.summary_dict()
        with _LOCK:
            t = _TENANTS.setdefault(self.tenant, {
                "execute_s": 0.0, "compile_s": 0.0, "host_s": 0.0,
                "idle_s": 0.0, "wall_s": 0.0, "scopes": 0.0,
            })
            for k in _KINDS:
                t[k] += out[k]
            t["wall_s"] += out["wall_s"]
            t["scopes"] += 1
            stages = _STAGES.setdefault(self.tenant, {})
            for name, st in out["stages"].items():
                agg = stages.setdefault(name, {
                    "execute_s": 0.0, "host_s": 0.0, "idle_s": 0.0, "events": 0.0,
                })
                agg["execute_s"] += st["execute_s"]
                agg["host_s"] += st["host_s"]
                agg["idle_s"] += st["idle_s"]
                agg["events"] += st["events"]
        reg = _registry()
        if reg is not None:
            reg.observe("efficiency.execute_s", out["execute_s"])
            reg.observe("efficiency.compile_s", out["compile_s"])
            reg.observe("efficiency.host_s", out["host_s"])
            reg.observe("efficiency.idle_s", out["idle_s"])
            if "mfu" in out:
                # serving windows gauge apart from fits: a scoring burst must
                # not overwrite the last fit's roofline reading
                if self.label.startswith("serve"):
                    reg.gauge("efficiency.serve_mfu", out["mfu"])
                else:
                    reg.gauge("efficiency.mfu", out["mfu"])
        return out


class _NoopScope:
    """Shared do-nothing scope: the disabled-telemetry path holds this one
    instance (identity-pinned by tests, like telemetry._NOOP_SPAN)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def close(self):
        return {}

    summary = None


_NOOP_SCOPE = _NoopScope()


class _ScopeCM:
    """Context manager wrapping one `_Scope`: sets the contextvar on entry,
    closes + restores on exit, and exposes the close summary as
    ``cm.summary`` for the caller's metrics stamp."""

    __slots__ = ("_scope", "summary")

    def __init__(self, scope: "_Scope"):
        self._scope = scope
        self.summary: Dict[str, Any] = {}

    def __enter__(self):
        self._scope._token = _SCOPE.set(self._scope)
        return self

    def __exit__(self, *exc):
        self.summary = self._scope.close()
        if self._scope._token is not None:
            _SCOPE.reset(self._scope._token)
        return False


def attribution_scope(
    label: str,
    *,
    tenant: Optional[str] = None,
    trace_id: Optional[str] = None,
):
    """Open one attribution window on this thread. Disabled telemetry (or a
    scope already active — scopes never nest) returns the shared no-op."""
    from .. import telemetry

    if not telemetry.enabled() or _SCOPE.get() is not None:
        return _NOOP_SCOPE
    if tenant is None:
        from ..scheduler.ledger import _current_tenant

        tenant = _current_tenant()
    return _ScopeCM(_Scope(label, str(tenant), trace_id))


def active() -> bool:
    """True when an attribution scope is open on this thread (the
    telemetry.py hooks probe this before building a timer)."""
    return _SCOPE.get() is not None


def note_flops(flops: float, *, chips: int = 1) -> None:
    """Record the active scope's analytic FLOP estimate (the
    `_solver_flop_estimate` hooks, docs/observability.md) — the MFU gauge's
    numerator. No-op outside a scope."""
    sc = _SCOPE.get()
    if sc is not None and flops and flops > 0:
        sc.flops += float(flops)
        sc.chips = max(sc.chips, int(chips))


# --------------------------------------------------------------- timers ----


class _Timer:
    """Times its body and attributes the wall to (kind, stage) on the
    active scope. Outermost-wins: nested timers attribute nothing."""

    __slots__ = ("kind", "stage", "_sc", "_t0", "_gap")

    def __init__(self, kind: str, stage: str):
        self.kind = kind
        self.stage = stage
        self._sc: Optional[_Scope] = None
        self._t0 = 0.0
        self._gap = 0.0

    def __enter__(self):
        sc = _SCOPE.get()
        if sc is not None and sc.depth == 0:
            self._sc = sc
            sc.depth += 1
            now = time.perf_counter()
            self._gap = max(0.0, now - sc.mark)
            self._t0 = now
        return self

    def __exit__(self, *exc):
        sc = self._sc
        if sc is not None:
            now = time.perf_counter()
            sc.depth -= 1
            sc.note(self.kind, self.stage, max(0.0, now - self._t0), self._gap)
            sc.mark = now
        return False


def device_wait_timer(stage: str) -> _Timer:
    return _Timer("execute_s", stage)


def host_section_timer(stage: str) -> _Timer:
    return _Timer("host_s", stage)


# -------------------------------------------------------- compile ledger ---


class _CompileEvent:
    """One jit entry-point execution, keyed (program, shape_key). First
    sighting = miss: the body's wall is recorded as compile time (known
    bias: it includes the first execution) and attributed to the active
    scope's compile kind. Later sightings = hit: counted, nothing timed.
    The ledger is process-wide — prewarm and autotune record with no scope
    active. ``cache_hit`` is readable after entry."""

    __slots__ = ("program", "shape_key", "cache_hit", "_t0", "_sc")

    def __init__(self, program: str, shape_key: str):
        self.program = program
        self.shape_key = str(shape_key)
        self.cache_hit = False
        self._t0 = 0.0
        self._sc: Optional[_Scope] = None

    def __enter__(self):
        key = (self.program, self.shape_key)
        with _COMPILE_LOCK:
            ent = _COMPILE.get(key)
            if ent is None:
                _COMPILE[key] = {"misses": 0.0, "hits": 0.0, "wall_s": 0.0}
                self.cache_hit = False
            else:
                self.cache_hit = True
        sc = _SCOPE.get()
        if self.cache_hit:
            if sc is not None:
                sc.compile_hits += 1
        else:
            self._t0 = time.perf_counter()
            if sc is not None and sc.depth == 0:
                self._sc = sc
                sc.depth += 1  # swallow inner waits: the miss wall wins
        return self

    def __exit__(self, *exc):
        key = (self.program, self.shape_key)
        reg = _registry()
        if self.cache_hit:
            with _COMPILE_LOCK:
                _COMPILE[key]["hits"] += 1
            if reg is not None:
                reg.inc("compile.hits")
            return False
        wall = max(0.0, time.perf_counter() - self._t0)
        with _COMPILE_LOCK:
            ent = _COMPILE[key]
            ent["misses"] += 1
            ent["wall_s"] += wall
        sc = self._sc
        if sc is not None:
            sc.depth -= 1
            sc.kinds["compile_s"] += wall
            sc.mark = time.perf_counter()
        cur = _SCOPE.get()
        if cur is not None:
            cur.compile_misses += 1
        if reg is not None:
            reg.inc("compile.misses")
            reg.observe("compile.wall_s", wall)
        return False


class _NoopCompileEvent:
    __slots__ = ()
    cache_hit = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP_COMPILE = _NoopCompileEvent()


def compile_event(program: str, shape_key: str):
    """Ledger a jit entry point (fit solve, PredictProgram prewarm rung,
    first-dispatch bucket, autotune measurement). Returns the shared no-op
    when telemetry is disabled."""
    from .. import telemetry

    if not telemetry.enabled():
        return _NOOP_COMPILE
    return _CompileEvent(program, shape_key)


def compile_stats() -> Dict[str, Any]:
    """The compile ledger rolled up: totals + per-(program, shape) entries."""
    with _COMPILE_LOCK:
        entries = [
            {
                "program": prog, "shape_key": shape,
                "misses": int(ent["misses"]), "hits": int(ent["hits"]),
                "wall_s": ent["wall_s"],
            }
            for (prog, shape), ent in _COMPILE.items()
        ]
    return {
        "programs": len(entries),
        "misses": sum(e["misses"] for e in entries),
        "hits": sum(e["hits"] for e in entries),
        "wall_s": sum(e["wall_s"] for e in entries),
        "entries": entries,
    }


# --------------------------------------------------------------- roll-up ---


def tenant_time_splits() -> Dict[str, Dict[str, float]]:
    """Per-tenant device-time splits for `HbmLedger.tenant_usage()`'s
    merge (the sys.modules probe in scheduler/ledger.py): tenant ->
    {execute_s, compile_s, host_s, idle_s, wall_s, scopes}."""
    with _LOCK:
        return {t: dict(v) for t, v in _TENANTS.items()}


def summary() -> Dict[str, Any]:
    """The efficiency plane as one JSON-able dict (`ops_plane.report()
    ["efficiency"]`): per-tenant kind splits with per-stage detail and the
    top idle-time stage, plus the compile ledger and the configured peak."""
    with _LOCK:
        tenants: Dict[str, Any] = {}
        for name, totals in _TENANTS.items():
            stages = {
                s: dict(v) for s, v in (_STAGES.get(name) or {}).items()
            }
            top = None
            if stages:
                top = max(stages, key=lambda s: stages[s]["idle_s"])
            tenants[name] = dict(totals)
            tenants[name]["stages"] = stages
            tenants[name]["top_idle_stage"] = top
    return {
        "tenants": tenants,
        "compile": compile_stats(),
        "device_peak_flops": peak_flops(),
    }


def reset() -> None:
    """Drop all accumulated state (test isolation)."""
    with _LOCK:
        _TENANTS.clear()
        _STAGES.clear()
    with _COMPILE_LOCK:
        _COMPILE.clear()
