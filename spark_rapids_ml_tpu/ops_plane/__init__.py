#
# Ops plane: the live operability layer over the telemetry registry
# (docs/observability.md "Ops plane").
#
# PRs 11-12 made the library a resident service (serving plane, fit
# scheduler); the PR-2/PR-5 telemetry stack was still batch-shaped —
# cumulative counters, sinks read after the run. This package is the
# other half: answers WHILE the process is up.
#
#   * rolling windows  — telemetry.MetricsRegistry's time-bucketed rings
#                        (rate()/window_quantile(); configured by
#                        `config["metrics_bucket_seconds"]` x
#                        `config["metrics_bucket_count"]`);
#   * export           — Prometheus/JSON scrape surface + /healthz on an
#                        opt-in `SRML_METRICS_PORT` http thread, and
#                        rotating on-disk snapshots for headless runs;
#   * slo              — declarative `config["slo"]` specs evaluated by
#                        multi-window burn rate, feeding /healthz and the
#                        flight recorder;
#   * audit            — the bounded per-tenant decision log (every
#                        admission/demotion/preemption/eviction verdict);
#   * drift            — per-column ingest feature stats + PSI-vs-baseline
#                        (ROADMAP item 5's observability half);
#   * efficiency       — the attribution plane: per-tenant device-time
#                        splits (execute/compile/host/idle), the jit
#                        compile ledger, and roofline/MFU gauges
#                        (docs/observability.md "Efficiency plane").
#
# `report()` is the one-call roll-up — live (`ops_plane.report()`), scraped
# (`GET /snapshot`), or archived (`export.write_snapshot()` ->
# `python -m benchmark.opsreport <file>`).
#
from __future__ import annotations

import os
import socket
import time
from typing import Any, Dict, Optional

from . import audit, drift, efficiency, export, fleet, slo
from .export import ensure_server, start_server, stop_server, write_snapshot

__all__ = [
    "audit",
    "drift",
    "efficiency",
    "export",
    "fleet",
    "slo",
    "report",
    "ensure_server",
    "start_server",
    "stop_server",
    "write_snapshot",
]


def _serving_section() -> Dict[str, Any]:
    """The serving plane's per-tenant overload view (backpressure ladder
    levels, refusal counters, tenant latency summaries) — every live
    ScoringEngine's controller, via `serving.overload.serving_report`."""
    try:
        from ..serving.overload import serving_report

        return serving_report()
    except Exception:  # pragma: no cover - the report never fails a scrape
        return {"tenants": {}}


def report(
    *,
    tenant: Optional[str] = None,
    trace_id: Optional[str] = None,
    decision_limit: int = 256,
    cluster: bool = False,
) -> Dict[str, Any]:
    """The full ops-plane state as one JSON-able dict: health + SLO verdicts
    (evaluated fresh), rolling-window rates/quantiles, the decision log
    (optionally filtered to one tenant / trace), per-tenant HBM accounting
    from the shared ledger, drift stats, and the registry snapshot. The
    `meta` header (rank/host/pid/t/trace id) and `windows_detail` (the
    age-indexed window export) are what the fleet plane's offline merger
    keys on — staleness, dead-rank detection, and cross-rank window
    alignment (docs/observability.md "Fleet plane"). `cluster=True` adds
    the last merged LIVE cluster view (`fleet.cluster_report()`)."""
    from .. import diagnostics, telemetry
    from ..ops import autotune as _autotune
    from ..scheduler.ledger import global_ledger

    reg = telemetry.registry()
    health = slo.health(fresh=True)
    rank = diagnostics._rank()
    now = time.time()
    rep = {
        "t": now,
        "meta": {
            "rank": rank,
            "hostname": socket.gethostname(),
            "pid": os.getpid(),
            "t": now,
            "trace_id": diagnostics.trace_tags().get("trace_id"),
        },
        "health": {k: health[k] for k in ("healthy", "failing", "specs")},
        "slo": health["verdicts"],
        "windows": reg.windows_snapshot(),
        "windows_detail": reg.windows_export(),
        "decisions": audit.decisions(
            tenant=tenant, trace_id=trace_id, limit=decision_limit
        ),
        "decision_log": audit.stats(),
        "tenants": global_ledger().tenant_usage(),
        "drift": drift.last_stats(),
        "serving": _serving_section(),
        "efficiency": efficiency.summary(),
        "autotune": {**_autotune.stats(), "table_path": _autotune.table_path()},
        "telemetry": reg.snapshot(),
    }
    if cluster:
        rep["cluster"] = fleet.cluster_report()
    return rep
