#
# Drift seedling: per-column feature statistics riding `validate_ingest`'s
# existing per-block scan (ROADMAP item 5's observability half — the refit
# TRIGGER's eyes, no refit logic yet).
#
# When `config["validate_ingest"]` is on, `data.validate_extracted` already
# walks every ingested row block chunk-by-chunk computing a finite mask.
# This module accumulates per-column running moments off that same pass —
# count, mean, std, non-finite ("null") fraction — at zero extra data
# passes, and publishes them as `ingest.feature.<col>.mean` /
# `.std` / `.null_fraction` gauges when the scan completes (streaming fits
# accumulate across their per-row-block calls and publish at the last
# block).
#
# PSI: register a baseline snapshot (`register_baseline(build_baseline(
# reference_extracted))`) and every subsequent scan also bins each column
# against the baseline's decile edges, publishing the population-stability
# index per column (`ingest.feature.<col>.psi`) and the max across columns
# (`ingest.feature.psi_max`) — the standard drift score (PSI > 0.2 is the
# conventional "investigate" line, docs/observability.md "Ops plane").
# Accumulation is skipped entirely while telemetry is disabled (the PR-2
# zero-cost contract) and on sparse ingests (a CSR block's per-column
# statistics would need a transpose pass the validation scan doesn't do).
#
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

from ..utils import lockcheck

__all__ = [
    "build_baseline",
    "register_baseline",
    "clear_baseline",
    "current_baseline",
    "accumulator_for",
    "last_stats",
]

_PSI_EPS = 1e-6


class Baseline:
    """Per-column reference distribution: decile bin edges + bin fractions
    (for PSI) and the reference moments. JSON-able via `to_dict`."""

    def __init__(
        self,
        edges: List[np.ndarray],
        fracs: List[np.ndarray],
        mean: np.ndarray,
        std: np.ndarray,
        null_fraction: np.ndarray,
        columns: List[str],
    ) -> None:
        self.edges = edges
        self.fracs = fracs
        self.mean = mean
        self.std = std
        self.null_fraction = null_fraction
        self.columns = columns

    @property
    def n_cols(self) -> int:
        return len(self.edges)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "columns": list(self.columns),
            "edges": [e.tolist() for e in self.edges],
            "fracs": [f.tolist() for f in self.fracs],
            "mean": self.mean.tolist(),
            "std": self.std.tolist(),
            "null_fraction": self.null_fraction.tolist(),
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Baseline":
        return cls(
            [np.asarray(e, dtype=np.float64) for e in d["edges"]],
            [np.asarray(f, dtype=np.float64) for f in d["fracs"]],
            np.asarray(d["mean"], dtype=np.float64),
            np.asarray(d["std"], dtype=np.float64),
            np.asarray(d["null_fraction"], dtype=np.float64),
            [str(c) for c in d["columns"]],
        )


_BASELINE_LOCK = lockcheck.make_lock("ops_plane.drift._BASELINE_LOCK")
_BASELINE: Optional[Baseline] = None  # guarded-by: _BASELINE_LOCK
# the most recent published stats (ops_plane.report()'s drift section)
_LAST_STATS: Optional[Dict[str, Any]] = None  # guarded-by: _BASELINE_LOCK


def build_baseline(
    extracted: Any, *, bins: int = 10, sample_rows: int = 100_000
) -> Baseline:
    """Snapshot a reference dataset's per-column distribution from a bounded
    row sample (deterministic head-stride sample — the baseline is a
    reference, not an estimator). Dense features only."""
    feats = extracted.features
    if hasattr(feats, "todense"):
        raise ValueError("drift baselines support dense feature blocks only")
    x = np.asarray(feats, dtype=np.float64)
    n = x.shape[0]
    if n > sample_rows:
        x = x[:: max(1, n // sample_rows)][:sample_rows]
    names = _column_names(extracted)
    edges: List[np.ndarray] = []
    fracs: List[np.ndarray] = []
    qs = np.linspace(0.0, 1.0, max(2, int(bins)) + 1)[1:-1]
    for c in range(x.shape[1]):
        col = x[:, c]
        col = col[np.isfinite(col)]
        if col.size == 0:
            e = np.array([0.0])
        else:
            e = np.unique(np.quantile(col, qs))
        counts = np.histogram(col, bins=np.concatenate(([-np.inf], e, [np.inf])))[0]
        total = max(1, int(counts.sum()))
        edges.append(e)
        fracs.append(counts / total)
    with np.errstate(invalid="ignore"):
        mask = np.isfinite(x)
        cnt = np.maximum(1, mask.sum(axis=0))
        xz = np.where(mask, x, 0.0)
        mean = xz.sum(axis=0) / cnt
        var = (xz * xz).sum(axis=0) / cnt - mean**2
    return Baseline(
        edges,
        fracs,
        mean,
        np.sqrt(np.maximum(0.0, var)),
        1.0 - mask.sum(axis=0) / max(1, x.shape[0]),
        names,
    )


def register_baseline(baseline: Baseline) -> None:
    global _BASELINE
    with _BASELINE_LOCK:
        _BASELINE = baseline


def clear_baseline() -> None:
    global _BASELINE
    with _BASELINE_LOCK:
        _BASELINE = None


def current_baseline() -> Optional[Baseline]:
    with _BASELINE_LOCK:
        return _BASELINE


def last_stats() -> Optional[Dict[str, Any]]:
    """The most recently published per-column stats (and PSI when a baseline
    was registered) — the `report()["drift"]` feed."""
    with _BASELINE_LOCK:
        return dict(_LAST_STATS) if _LAST_STATS else None


def _column_names(extracted: Any) -> List[str]:
    n = int(extracted.n_cols)
    names = list(getattr(extracted, "feature_names", []) or [])
    if len(names) == n:
        return [str(c) for c in names]
    return [str(i) for i in range(n)]


class DriftAccumulator:
    """Running per-column moments (+ optional baseline bin counts) fed one
    validation chunk at a time. One accumulator per ExtractedData scan; the
    streaming path's per-row-block calls share it across blocks."""

    def __init__(self, extracted: Any) -> None:
        d = int(extracted.n_cols)
        self.columns = _column_names(extracted)
        self.rows = 0
        self.finite = np.zeros(d, dtype=np.int64)
        self.sum = np.zeros(d, dtype=np.float64)
        self.sumsq = np.zeros(d, dtype=np.float64)
        self.baseline = current_baseline()
        if self.baseline is not None and self.baseline.n_cols != d:
            self.baseline = None  # a baseline for a different width is noise
        self.bin_counts: Optional[List[np.ndarray]] = (
            [np.zeros(len(b) + 1, dtype=np.int64) for b in self.baseline.edges]
            if self.baseline is not None
            else None
        )
        self.published = False

    def update(self, chunk: np.ndarray) -> None:
        if chunk.ndim == 1:
            chunk = chunk[:, None]
        x = np.asarray(chunk, dtype=np.float64)
        mask = np.isfinite(x)
        self.rows += int(x.shape[0])
        self.finite += mask.sum(axis=0)
        xz = np.where(mask, x, 0.0)
        self.sum += xz.sum(axis=0)
        self.sumsq += (xz * xz).sum(axis=0)
        if self.bin_counts is not None and self.baseline is not None:
            for c, edges in enumerate(self.baseline.edges):
                col = x[:, c][mask[:, c]]
                self.bin_counts[c] += np.histogram(
                    col, bins=np.concatenate(([-np.inf], edges, [np.inf]))
                )[0]

    def stats(self) -> Dict[str, Any]:
        cnt = np.maximum(1, self.finite)
        mean = self.sum / cnt
        var = np.maximum(0.0, self.sumsq / cnt - mean**2)
        out: Dict[str, Any] = {
            "rows": self.rows,
            "columns": list(self.columns),
            "mean": mean.tolist(),
            "std": np.sqrt(var).tolist(),
            "null_fraction": (
                1.0 - self.finite / max(1, self.rows)
            ).tolist(),
        }
        if self.bin_counts is not None and self.baseline is not None:
            psis = []
            for c, counts in enumerate(self.bin_counts):
                total = max(1, int(counts.sum()))
                actual = np.maximum(counts / total, _PSI_EPS)
                ref = np.maximum(self.baseline.fracs[c], _PSI_EPS)
                psis.append(float(np.sum((actual - ref) * np.log(actual / ref))))
            out["psi"] = psis
            out["psi_max"] = max(psis) if psis else 0.0
        return out

    def publish(self) -> Optional[Dict[str, Any]]:
        """Gauge the accumulated stats (idempotent per scan)."""
        global _LAST_STATS
        from .. import telemetry

        if self.published or not self.rows:
            return None
        self.published = True
        stats = self.stats()
        if telemetry.enabled():
            reg = telemetry.registry()
            for i, col in enumerate(self.columns):
                reg.gauge(f"ingest.feature.{col}.mean", stats["mean"][i])
                reg.gauge(f"ingest.feature.{col}.std", stats["std"][i])
                reg.gauge(
                    f"ingest.feature.{col}.null_fraction", stats["null_fraction"][i]
                )
                if "psi" in stats:
                    reg.gauge(f"ingest.feature.{col}.psi", stats["psi"][i])
            if "psi_max" in stats:
                reg.gauge("ingest.feature.psi_max", stats["psi_max"])
        with _BASELINE_LOCK:
            _LAST_STATS = stats
        return stats


def accumulator_for(extracted: Any) -> Optional[DriftAccumulator]:
    """The scan's accumulator, created on first ask and cached on the
    ExtractedData record (streaming per-block validation calls share it).
    None — and zero cost — while telemetry is disabled or the block is
    sparse."""
    from .. import telemetry

    if not telemetry.enabled() or extracted.is_sparse:
        return None
    acc = getattr(extracted, "_drift_acc", None)
    if acc is None:
        acc = DriftAccumulator(extracted)
        extracted._drift_acc = acc
    return acc
