#
# Distributed diagnostics: cross-rank trace correlation, an always-on flight
# recorder, and the post-mortem / trace-merge assemblers built on both.
#
# The barrier-mode design (PAPER.md) makes every fit a lockstep dance across
# ranks, but per-rank telemetry files observe each rank in isolation. This
# module is the correlation layer on top of the telemetry registry (PR 2) and
# the fault-tolerant control plane (PR 3):
#
#   * TRACE CORRELATION — every fit runs inside `trace_scope()`: rank 0 mints
#     a `trace_id`, propagates it through one rendezvous round at trace begin
#     (the Dapper pattern: the id rides the control plane the fit already
#     trusts), and every span / fit / flight-recorder record emitted during
#     the scope carries `trace_id` + `fit_id` + rank. `merge_chrome_trace`
#     turns the per-rank telemetry JSONL files into one Chrome trace-event
#     JSON (one track per rank, rendezvous rounds as flow arrows, clock skew
#     aligned on barrier rounds) loadable in Perfetto / chrome://tracing.
#   * FLIGHT RECORDER — a bounded, always-on, lock-cheap per-rank ring of
#     structured events (span begin/end, rendezvous round enter/exit, solver
#     ticks, chaos injections, retry attempts; control-plane events record
#     unconditionally, span/solver events only while telemetry is enabled —
#     disabled spans are a no-op object with nothing to record, the PR-2
#     zero-cost contract). On any `SrmlError` the ring
#     is dumped to `flightrec_rank_<r>.jsonl` (when a dump dir is configured)
#     and the last-K events are attached to the exception as
#     ``exc.flightrec_tail`` — "the failure already happened; what was
#     everyone doing?" answered without re-running.
#   * POST-MORTEM — `assemble_postmortem` correlates all ranks' dumps by
#     trace id into one timeline naming the failed rank, the round it died
#     in, and what every survivor was blocked on when it noticed.
#
# Contracts:
#   * ALWAYS ON, NEAR-FREE: recording an event is one time.time() + one dict
#     + one lock'd ring write; no I/O until a dump is requested. Disable
#     entirely with SRML_FLIGHTREC=0.
#   * NO SILENT CAPS (PR-2 convention): ring overwrites are counted — the
#     recorder's `stats()["dropped"]`, the `flightrec.events_dropped`
#     registry counter, and a `telemetry.summary()` health line all surface
#     truncation.
#   * NO COLLECTIVES OF ITS OWN except the single trace-id round inside
#     `trace_scope` under SPMD — which runs in lockstep on every rank, at a
#     point where the control plane is already live.
#
from __future__ import annotations

import contextlib
import contextvars
import itertools
import json
import os
import re
import sys
import threading
import time
import uuid
from typing import Any, Dict, Iterable, List, Optional, Tuple

from .utils import lockcheck

__all__ = [
    "trace_scope",
    "current_trace",
    "trace_tags",
    "set_process_rank",
    "FlightRecorder",
    "flight_recorder",
    "record_event",
    "on_srml_error",
    "flightrec_dir",
    "flightrec_dump_path",
    "load_flightrec_dumps",
    "assemble_postmortem",
    "render_postmortem",
    "load_telemetry_jsonl",
    "merge_chrome_trace",
    "chrome_trace_from_files",
]

FLIGHTREC_FILE_PREFIX = "flightrec_rank_"

# Default ring capacity / exception-tail length. Both env-overridable; the
# capacity bound is what keeps "always-on" honest on a long-lived process.
_DEFAULT_CAPACITY = 2048
_DEFAULT_TAIL = 25


# Process-rank override for launchers that run no TpuContext (the subprocess
# chaos harness, bare-rendezvous drivers): without it every worker would tag
# events rank 0 and clobber one shared flightrec_rank_0.jsonl dump.
_PROCESS_RANK: Optional[int] = None


def set_process_rank(rank: int) -> None:
    """Pin this process's rank for record tagging + dump naming when no
    `TpuContext` is entered (an active context always wins). The `SRML_RANK`
    env var is the no-code-change equivalent for subprocess launchers."""
    global _PROCESS_RANK
    _PROCESS_RANK = int(rank)


def _rank() -> int:
    """This rank, for event tagging: active TpuContext > `set_process_rank`
    > `SRML_RANK` env > 0. Control-plane only (never initializes an XLA
    backend). telemetry._rank delegates here, so the JSONL sink's per-rank
    file naming follows the same resolution."""
    try:
        from .parallel.context import TpuContext

        ctx = TpuContext.current()
        if ctx is not None:
            return ctx.rank
    except Exception:  # pragma: no cover - import cycles during teardown
        pass
    if _PROCESS_RANK is not None:
        return _PROCESS_RANK
    env = os.environ.get("SRML_RANK")
    if env:
        try:
            return int(env)
        except ValueError:
            pass
    return 0


# ------------------------------------------------------- trace correlation --

# The active trace, context-local so concurrent fits on different threads
# carry their own ids (same isolation argument as core's DeviceDataset scope).
_TRACE: "contextvars.ContextVar[Optional[Dict[str, Any]]]" = contextvars.ContextVar(
    "srml_trace", default=None
)
_FIT_SEQ = itertools.count(1)

# Payload prefix for the trace-id rendezvous round — versioned so a future
# format change is detectable instead of silently misparsed.
_TRACE_ROUND_PREFIX = "TRACE1:"


def current_trace() -> Optional[Dict[str, Any]]:
    """The active trace dict ``{"trace_id", "fit_id"}``, or None."""
    return _TRACE.get()


def trace_tags() -> Dict[str, Any]:
    """Tags every span/metric/flight-recorder record should carry. Inside a
    `trace_scope` these are the scope's ids; outside one, a launcher-minted
    ``SRML_TRACE_ID`` (the subprocess-harness path: one env id correlates all
    ranks of a run without any in-band exchange) still tags records."""
    t = _TRACE.get()
    if t is not None:
        return t
    env_id = os.environ.get("SRML_TRACE_ID")
    if env_id:
        return {"trace_id": env_id}
    return {}


@contextlib.contextmanager
def trace_scope(label: str, ctx: Any = None):
    """Mint + propagate the per-fit trace identity for the dynamic extent.

    ``fit_id`` is a process-local sequence number ("fit-<n>"); under lockstep
    barrier execution every rank's counter advances identically, so it agrees
    across ranks without communication. ``trace_id`` must be GLOBALLY unique
    and identical on all ranks: single-controller mints locally (or adopts a
    launcher's ``SRML_TRACE_ID``); SPMD mints on rank 0 and propagates the id
    through one rendezvous round at trace begin — every rank enters the round
    in lockstep, so this adds exactly one control-plane round per fit.

    NESTED scopes ADOPT the enclosing trace_id (Dapper semantics: a
    CrossValidator fit is ONE trace; each fold/refit inside it gets its own
    fit_id under that trace) and skip the rendezvous exchange — the outer
    scope already coordinated the id."""
    fit_id = f"fit-{next(_FIT_SEQ)}"
    outer = _TRACE.get()
    if outer is not None:
        trace_id = outer["trace_id"]
    else:
        trace_id = os.environ.get("SRML_TRACE_ID") or uuid.uuid4().hex[:16]
        rendezvous = getattr(ctx, "rendezvous", None)
        if ctx is not None and getattr(ctx, "is_spmd", False) and rendezvous is not None:
            # the exchange is NON-FATAL: this round runs before the fit body
            # enters core.retryable_stage, so an error here would bypass the
            # retry machinery — and diagnostics must never turn a working
            # fit into a failed one. On failure, fall back to the local id
            # (degraded correlation, fit proceeds); a genuinely broken
            # control plane surfaces at the fit's own next round, WITH retry
            # protection, and the typed desync guards cover any round-count
            # divergence a one-sided timeout could leave behind.
            try:
                # the fleet plane piggybacks its ops-round scheduling on this
                # round (docs/observability.md "Fleet plane"): rank 0 ALONE
                # evaluates the time throttle and broadcasts the decision as
                # a `|ops` suffix — a per-rank local throttle would desync
                # the lockstep round counters. sys.modules probe: trace
                # exchange must not pay the ops_plane import chain, and a
                # process that never imported the fleet plane runs zero ops
                # rounds. Trace ids are hex, so "|" cannot collide.
                fleet = sys.modules.get(__package__ + ".ops_plane.fleet")
                flag = (
                    "|" + fleet.OPS_ROUND_FLAG
                    if fleet is not None and ctx.rank == 0 and fleet.ops_due()
                    else ""
                )
                payload = _TRACE_ROUND_PREFIX + (trace_id if ctx.rank == 0 else "") + flag
                gathered = rendezvous.allgather(payload)
                root = gathered[0]
                ops_follows = False
                if root.startswith(_TRACE_ROUND_PREFIX):
                    rest = root[len(_TRACE_ROUND_PREFIX):]
                    rid, sep, tail = rest.partition("|")
                    if rid:
                        trace_id = rid
                    if sep and "ops" in tail.split("|"):
                        if fleet is None:
                            # rank 0 runs the fleet plane but this process
                            # never imported it — import now rather than
                            # desync the lockstep round rank 0 is entering
                            from .ops_plane import fleet  # noqa: PLC0415
                        ops_follows = True
                if ops_follows:
                    # every rank saw the same root payload, so every rank
                    # enters the ops round in lockstep — including ranks
                    # whose local telemetry is off (they send the bare
                    # marker). ops_round never raises (non-fatal contract).
                    fleet.ops_round(rendezvous)
            except Exception as e:
                record_event("trace_exchange_failed", label=label,
                             error=type(e).__name__)
    tags = {"trace_id": trace_id, "fit_id": fit_id}
    token = _TRACE.set(tags)
    record_event("trace_begin", label=label)
    try:
        yield dict(tags)
    finally:
        record_event("trace_end", label=label)
        _TRACE.reset(token)


# --------------------------------------------------------- flight recorder --


class FlightRecorder:
    """Bounded always-on ring buffer of structured diagnostic events.

    `record` is the hot call: one wall-clock read, one small dict, one lock'd
    slot write. The ring OVERWRITES oldest-first at capacity; overwrites are
    counted (never silent — `stats()`, the `flightrec.events_dropped` registry
    counter, and the `telemetry.summary()` health line all expose them)."""

    def __init__(self, capacity: Optional[int] = None, enabled: Optional[bool] = None):
        if capacity is None:
            try:
                capacity = int(os.environ.get("SRML_FLIGHTREC_EVENTS", _DEFAULT_CAPACITY))
            except ValueError:  # a typo'd knob must not crash module import
                capacity = _DEFAULT_CAPACITY
        if enabled is None:
            enabled = os.environ.get("SRML_FLIGHTREC", "1") not in ("0", "false", "off")
        self.capacity = max(1, int(capacity))
        self.enabled = bool(enabled)
        self._lock = lockcheck.make_lock("diagnostics.FlightRecorder._lock")
        self._buf: List[Optional[Dict[str, Any]]] = [None] * self.capacity  # guarded-by: _lock
        self._next = 0  # next slot to write  # guarded-by: _lock
        self._total = 0  # events ever recorded  # guarded-by: _lock
        self._dropped = 0  # events overwritten (total - retained)  # guarded-by: _lock

    # -- record (the hot path) ---------------------------------------------
    def record(self, kind: str, **fields: Any) -> None:
        if not self.enabled:
            return
        ev = {"t": time.time(), "kind": kind, "rank": _rank(), **trace_tags(), **fields}
        with self._lock:
            dropped = self._buf[self._next] is not None
            self._buf[self._next] = ev
            self._next = (self._next + 1) % self.capacity
            self._total += 1
            if dropped:
                self._dropped += 1
        if dropped:
            # surface truncation through the registry too (when telemetry is
            # on) so it rides model._fit_metrics and the bench snapshot
            try:
                from . import telemetry

                telemetry.registry().inc("flightrec.events_dropped")
            except Exception:  # pragma: no cover - teardown ordering
                pass

    # -- read --------------------------------------------------------------
    def events(self) -> List[Dict[str, Any]]:
        """All retained events, oldest first."""
        with self._lock:
            ordered = self._buf[self._next:] + self._buf[: self._next]
        return [dict(e) for e in ordered if e is not None]

    def tail(self, k: int = _DEFAULT_TAIL) -> List[Dict[str, Any]]:
        """The newest `k` retained events, oldest first. ``k <= 0`` means no
        tail (NOT the whole ring — evs[-0:] would be everything)."""
        if k <= 0:
            return []
        evs = self.events()
        return evs[-k:] if k < len(evs) else evs

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "enabled": self.enabled,
                "capacity": self.capacity,
                "recorded": self._total,
                "retained": min(self._total, self.capacity) if self.enabled else 0,
                "dropped": self._dropped,
            }

    def reset(self) -> None:
        with self._lock:
            self._buf = [None] * self.capacity
            self._next = 0
            self._total = 0
            self._dropped = 0

    # -- dump --------------------------------------------------------------
    def dump(self, path: Optional[str] = None, reason: str = "") -> Optional[str]:
        """Write the whole retained ring as JSONL (one event per line, plus a
        trailing ``{"kind": "flightrec_dump"}`` footer carrying stats + the
        dump reason). `path` defaults to ``flightrec_rank_<r>.jsonl`` under
        the configured dump dir; no dir configured -> no file, returns None.
        Write-then-rename so a concurrently-assembling post-mortem never reads
        a torn file. Each dump is a full snapshot (later dumps supersede)."""
        if not self.enabled:
            return None
        if path is None:
            path = flightrec_dump_path()
            if path is None:
                return None
        footer = {"kind": "flightrec_dump", "t": time.time(), "rank": _rank(),
                  "reason": reason, **trace_tags(), **self.stats()}
        tmp = f"{path}.tmp{os.getpid()}"
        try:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            with open(tmp, "w") as f:
                for ev in self.events():
                    f.write(json.dumps(ev, default=str) + "\n")  # sink-ok: flight-recorder dump owner
                f.write(json.dumps(footer, default=str) + "\n")  # sink-ok: flight-recorder dump owner
            os.replace(tmp, path)
        except OSError:  # pragma: no cover - dump is best-effort by design
            with contextlib.suppress(OSError):
                os.unlink(tmp)
            return None
        return path


_RECORDER = FlightRecorder()


def flight_recorder() -> FlightRecorder:
    return _RECORDER


def record_event(kind: str, **fields: Any) -> None:
    """Module-level convenience over the process recorder (the call sites in
    telemetry/context/chaos/core use this)."""
    _RECORDER.record(kind, **fields)


def flightrec_dir() -> Optional[str]:
    """Dump directory: ``SRML_FLIGHTREC_DIR`` env, else
    ``config["flightrec_dir"]``. None -> exception tails still attach, but no
    dump files are written.

    The config fallback consults `sys.modules` instead of importing: this
    runs inside SrmlError construction, and control-plane-only processes
    (the rendezvous harness) may never have loaded `core` — paying its full
    import chain (numpy/pandas) HERE would add ~1s to every survivor's
    failure-detection latency, measured blowing the 2x-heartbeat budget. If
    `core` was never imported, its config cannot have been customized."""
    d = os.environ.get("SRML_FLIGHTREC_DIR")
    if d:
        return d
    core = sys.modules.get(__package__ + ".core")
    if core is not None:
        try:
            return core.config.get("flightrec_dir") or None
        except Exception:  # pragma: no cover - partially-initialized module
            return None
    return None


def flightrec_dump_path(rank: Optional[int] = None) -> Optional[str]:
    d = flightrec_dir()
    if not d:
        return None
    r = _rank() if rank is None else rank
    return os.path.join(d, f"{FLIGHTREC_FILE_PREFIX}{r}.jsonl")


def on_srml_error(exc: BaseException) -> None:
    """Called from ``SrmlError.__init__``: record the error as a ring event,
    attach the last-K events to the exception (``exc.flightrec_tail``), and
    dump the ring to the per-rank file. Must never raise — a diagnostics
    failure must not mask the error being constructed."""
    if not _RECORDER.enabled:
        return
    fields: Dict[str, Any] = {"error": type(exc).__name__, "message": str(exc)[:500]}
    for attr in ("failed_rank", "round_index", "missing_ranks", "reason",
                 "solver", "iteration", "column"):
        v = getattr(exc, attr, None)
        if v is not None:
            fields[attr] = v
    _RECORDER.record("error", **fields)
    try:
        k = int(os.environ.get("SRML_FLIGHTREC_TAIL", _DEFAULT_TAIL))
    except ValueError:
        k = _DEFAULT_TAIL
    exc.flightrec_tail = _RECORDER.tail(k)
    dumped = _RECORDER.dump(reason=f"{type(exc).__name__}: {str(exc)[:200]}")
    if dumped is not None:
        # ride an ops-plane snapshot (SLO verdicts, decision log, tenant
        # accounting) next to the flight-recorder dump, so a post-mortem
        # carries the VERDICT context too. sys.modules probe, same argument
        # as flightrec_dir: error construction must never pay an import
        # chain, and a process that never loaded the ops plane has no ops
        # state to snapshot.
        ops = sys.modules.get(__package__ + ".ops_plane")
        if ops is not None:
            try:
                ops.export.write_snapshot(
                    os.path.join(os.path.dirname(dumped),
                                 f"ops_snapshot_rank_{_rank()}.json")
                )
            except Exception:  # pragma: no cover - snapshot is best-effort
                pass


# ------------------------------------------------------------- post-mortem --


def load_flightrec_dumps(
    dump_dir: str, nranks: Optional[int] = None
) -> Tuple[Dict[int, List[Dict[str, Any]]], List[int]]:
    """Read every ``flightrec_rank_<r>.jsonl`` under `dump_dir`. Returns
    (events per rank, missing ranks). A rank is MISSING when `nranks` says it
    should exist but no dump is present — a SIGKILLed process writes nothing,
    so absence is itself evidence."""
    per_rank: Dict[int, List[Dict[str, Any]]] = {}
    pat = re.compile(re.escape(FLIGHTREC_FILE_PREFIX) + r"(\d+)\.jsonl$")
    if os.path.isdir(dump_dir):
        for name in sorted(os.listdir(dump_dir)):
            m = pat.match(name)
            if not m:
                continue
            events: List[Dict[str, Any]] = []
            with open(os.path.join(dump_dir, name)) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        events.append(json.loads(line))
                    except ValueError:
                        continue  # torn/garbage line — keep what parses
            per_rank[int(m.group(1))] = events
    expected = range(nranks) if nranks else []
    missing = [r for r in expected if r not in per_rank]
    return per_rank, missing


def _latest_trace_id(per_rank: Dict[int, List[Dict[str, Any]]]) -> Optional[str]:
    """The most recently seen trace id across all dumps (dumps may hold
    events from several fits; post-mortems care about the one that died)."""
    best_t, best_id = float("-inf"), None
    for events in per_rank.values():
        for ev in events:
            tid = ev.get("trace_id")
            if tid and ev.get("t", 0) > best_t:
                best_t, best_id = ev["t"], tid
    return best_id


def assemble_postmortem(
    dump_dir: str,
    nranks: Optional[int] = None,
    trace_id: Optional[str] = None,
    last_k: int = _DEFAULT_TAIL,
) -> Dict[str, Any]:
    """Correlate all ranks' flight-recorder dumps into one failure timeline.

    Returns a machine-readable dict:
      * ``failed_rank`` / ``failed_round`` / ``failure_reason`` — majority
        verdict of the survivors' recorded errors (``RankFailedError`` events
        name the rank they blame), with a rank whose dump is MISSING promoted
        to prime suspect (hard-killed processes write nothing);
      * ``ranks`` — per rank: last-K events, the last rendezvous round it
        entered, and what it was blocked on when the failure surfaced;
      * ``timeline`` — every rank's events merged and time-sorted.
    """
    per_rank, missing = load_flightrec_dumps(dump_dir, nranks)
    if trace_id is None:
        trace_id = _latest_trace_id(per_rank)
    if trace_id is not None:
        per_rank = {
            r: [e for e in evs if e.get("trace_id") in (trace_id, None)]
            for r, evs in per_rank.items()
        }

    blame: Dict[int, int] = {}
    missing_votes: Dict[int, int] = {}  # RendezvousTimeoutError.missing_ranks
    blame_round: Dict[int, int] = {}
    reasons: List[str] = []
    ranks: Dict[int, Dict[str, Any]] = {}
    timeline: List[Dict[str, Any]] = []
    # recovery epochs (elastic recovery): every reform a rank recorded,
    # deduped by generation — the post-mortem NAMES each epoch, its survivor
    # set, and the dead ranks it excluded
    recovery_epochs: Dict[int, Dict[str, Any]] = {}
    for r, events in sorted(per_rank.items()):
        timeline.extend(events)
        last_enter: Optional[Dict[str, Any]] = None
        blocked_on: Optional[str] = None
        open_round: Optional[Dict[str, Any]] = None
        for ev in events:
            k = ev.get("kind")
            if k == "rdv_enter":
                open_round = ev
                last_enter = ev
            elif k in ("rdv_exit", "rdv_fail"):
                open_round = None
            elif k in ("recovery_reform", "recovery_epoch_begin", "chaos_reform"):
                gen = ev.get("generation")
                if gen is not None:
                    entry = recovery_epochs.setdefault(
                        int(gen), {"generation": int(gen)}
                    )
                    if ev.get("survivors") is not None:
                        entry["survivors"] = list(ev["survivors"])
                    if ev.get("dead") is not None:
                        entry["dead"] = sorted(ev["dead"])
                    elif ev.get("dead_ranks") is not None:
                        entry.setdefault("dead", sorted(ev["dead_ranks"]))
            elif k == "error":
                fr = ev.get("failed_rank")
                if fr is not None:
                    blame[int(fr)] = blame.get(int(fr), 0) + 1
                for m in ev.get("missing_ranks") or []:
                    # timeout-shaped failure: nobody published, but the
                    # survivor recorded WHO it was still waiting on
                    missing_votes[int(m)] = missing_votes.get(int(m), 0) + 1
                rnd = ev.get("round_index")
                if rnd is not None:
                    blame_round[int(rnd)] = blame_round.get(int(rnd), 0) + 1
                if ev.get("reason"):
                    reasons.append(str(ev["reason"]))
                elif ev.get("message"):
                    reasons.append(str(ev["message"]))
        if open_round is not None:
            blocked_on = f"rendezvous round {open_round.get('round')}"
        errs = [e for e in events if e.get("kind") == "error"]
        ranks[r] = {
            "events": len(events),
            "last_events": events[-last_k:],
            "last_round_entered": last_enter.get("round") if last_enter else None,
            "blocked_on": blocked_on,
            "error": errs[-1].get("error") if errs else None,
        }
    timeline.sort(key=lambda e: e.get("t", 0.0))

    failed_rank: Optional[int] = None
    failed_round: Optional[int] = None
    if blame:
        # strongest evidence: survivors' errors NAMED the rank (abort
        # sentinel or heartbeat staleness)
        failed_rank = max(blame, key=lambda r: blame[r])
    elif missing_votes:
        # timeout-shaped: nobody published, but survivors recorded who they
        # were still waiting on when the deadline fired
        failed_rank = max(missing_votes, key=lambda r: missing_votes[r])
    elif missing and per_rank:
        # absence as evidence — but only when at least one rank DID report;
        # an empty dump dir is "no evidence", not "rank 0 failed"
        failed_rank = missing[0]
    if blame_round:
        failed_round = max(blame_round, key=lambda k: blame_round[k])
    if failed_round is None and failed_rank is not None and failed_rank in ranks:
        failed_round = ranks[failed_rank].get("last_round_entered")

    return {
        "trace_id": trace_id,
        "nranks": nranks if nranks is not None else len(per_rank),
        "ranks_reporting": sorted(per_rank),
        "missing_ranks": missing,
        "failed_rank": failed_rank,
        "failed_round": failed_round,
        "failure_reason": reasons[0] if reasons else None,
        "recovery_epochs": [
            recovery_epochs[g] for g in sorted(recovery_epochs)
        ],
        "ranks": ranks,
        "timeline": timeline,
    }


def render_postmortem(pm: Dict[str, Any]) -> str:
    """Human-readable rendering of an `assemble_postmortem` result."""
    lines = [
        f"POST-MORTEM trace={pm.get('trace_id') or '?'} "
        f"({len(pm.get('ranks_reporting', []))}/{pm.get('nranks', '?')} ranks reporting)"
    ]
    fr, rd = pm.get("failed_rank"), pm.get("failed_round")
    if fr is not None:
        where = f" at round {rd}" if rd is not None else ""
        lines.append(f"verdict: rank {fr} failed{where}")
        if pm.get("failure_reason"):
            lines.append(f"reason: {pm['failure_reason']}")
    else:
        lines.append("verdict: no failure evidence found")
    if pm.get("missing_ranks"):
        lines.append(
            f"missing dumps (hard-killed? never started?): ranks {pm['missing_ranks']}"
        )
    for ep in pm.get("recovery_epochs") or []:
        dead = f", excluded {ep['dead']}" if ep.get("dead") else ""
        lines.append(
            f"recovery epoch g{ep.get('generation')}: survivors "
            f"{ep.get('survivors')}{dead} — the fit CONTINUED on the "
            "reformed group"
        )
    for r, info in sorted(pm.get("ranks", {}).items()):
        status = info.get("error") or (
            f"blocked on {info['blocked_on']}" if info.get("blocked_on") else "ran to dump"
        )
        lines.append(
            f"  rank {r}: {info['events']} events, "
            f"last round entered {info.get('last_round_entered')}, {status}"
        )
        for ev in info.get("last_events", [])[-5:]:
            detail = {
                k: v for k, v in ev.items()
                if k not in ("t", "kind", "rank", "trace_id", "fit_id")
            }
            lines.append(f"    {ev.get('t', 0):.3f} {ev.get('kind')} {detail or ''}")
    return "\n".join(lines)


# -------------------------------------------------------------- trace merge --


def load_telemetry_jsonl(base_path: str) -> Dict[int, List[Dict[str, Any]]]:
    """Discover + read the per-rank telemetry JSONL family: rank 0 owns
    `base_path`, rank r writes ``<base_path>.rank<r>`` (telemetry sink
    contract). Missing / empty / ragged files are fine — you merge what you
    have."""
    per_rank: Dict[int, List[Dict[str, Any]]] = {}
    candidates: List[Tuple[int, str]] = []
    if os.path.exists(base_path):
        candidates.append((0, base_path))
    d = os.path.dirname(os.path.abspath(base_path)) or "."
    base_name = os.path.basename(base_path)
    if os.path.isdir(d):
        pat = re.compile(re.escape(base_name) + r"\.rank(\d+)$")
        for name in os.listdir(d):
            m = pat.match(name)
            if m:
                candidates.append((int(m.group(1)), os.path.join(d, name)))
    for rank, path in sorted(candidates):
        records: List[Dict[str, Any]] = []
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict):
                        records.append(rec)
        except OSError:
            continue
        per_rank[rank] = records
    return per_rank


def _span_end(rec: Dict[str, Any]) -> Optional[float]:
    t0, wall = rec.get("t0"), rec.get("wall_s")
    if t0 is None or wall is None:
        return None
    return float(t0) + float(wall)


def _round_key(rec: Dict[str, Any]) -> Tuple:
    """Identity of one lockstep rendezvous round, unique across retries and
    across the fits sharing a trace: round counters reset on `begin_epoch`
    (retry attempts) and fits interleave under one CV trace, so the bare
    round index collides — (trace, fit, epoch, round) cannot. Every field
    agrees across ranks: fit_id advances in lockstep, epoch/round come from
    the rendezvous the ranks synchronized through."""
    return (rec.get("trace_id"), rec.get("fit_id"), rec.get("epoch"), rec["round"])


def _barrier_offsets(per_rank: Dict[int, List[Dict[str, Any]]]) -> Dict[int, float]:
    """Clock-skew offsets per rank, anchored on rank 0 (or the lowest rank
    present). Barrier rounds are the sync points: all ranks LEAVE a
    rendezvous round at (physically) the same instant, so for every round
    both sides recorded, ``anchor_end - rank_end`` samples that rank's clock
    offset; the median over rounds rejects outliers (a slow record on one
    side). Ranks sharing no rounds with the anchor get offset 0."""
    ends: Dict[int, Dict[Any, float]] = {}
    for r, recs in per_rank.items():
        by_round: Dict[Any, float] = {}
        for rec in recs:
            if rec.get("kind") != "span" or rec.get("name") != "rendezvous.allgather":
                continue
            end = _span_end(rec)
            if rec.get("round") is None or end is None:
                continue
            by_round[_round_key(rec)] = end
        if by_round:
            ends[r] = by_round
    offsets: Dict[int, float] = {r: 0.0 for r in per_rank}
    if not ends:
        return offsets
    anchor = min(ends)
    for r, by_round in ends.items():
        if r == anchor:
            continue
        deltas = sorted(
            ends[anchor][k] - v for k, v in by_round.items() if k in ends[anchor]
        )
        if deltas:
            offsets[r] = deltas[len(deltas) // 2]
    return offsets


def merge_chrome_trace(
    per_rank: Dict[int, List[Dict[str, Any]]],
    *,
    trace_id: Optional[str] = None,
    align_clocks: bool = True,
) -> Dict[str, Any]:
    """Merge per-rank telemetry JSONL records into Chrome trace-event JSON
    (the Perfetto / chrome://tracing "JSON Array Format" with metadata):

      * one track (``tid``) per rank under one process (``pid`` 0), named via
        ``thread_name`` metadata events;
      * every span record becomes a complete ("X") event at its recorded
        wall-clock start, duration ``wall_s`` — microsecond units, rebased to
        the earliest aligned timestamp;
      * rendezvous rounds become flow arrows (``s``/``f`` events bound by
        round id) from the anchor rank's round exit to every other rank's —
        the lockstep structure made visible;
      * clock skew is corrected per rank using barrier rounds as sync points
        (`align_clocks`; see `_barrier_offsets`).
    """
    if trace_id is not None:
        per_rank = {
            r: [rec for rec in recs if rec.get("trace_id") == trace_id]
            for r, recs in per_rank.items()
        }
    offsets = _barrier_offsets(per_rank) if align_clocks else {r: 0.0 for r in per_rank}

    starts = [
        rec["t0"] + offsets.get(r, 0.0)
        for r, recs in per_rank.items()
        for rec in recs
        if rec.get("kind") == "span" and rec.get("t0") is not None
    ]
    base = min(starts) if starts else 0.0

    events: List[Dict[str, Any]] = [
        {"ph": "M", "name": "process_name", "pid": 0, "tid": 0,
         "args": {"name": f"srml trace {trace_id or 'all'}"}},
    ]
    flow_ends: Dict[Any, Dict[int, float]] = {}
    for r in sorted(per_rank):
        events.append(
            {"ph": "M", "name": "thread_name", "pid": 0, "tid": r,
             "args": {"name": f"rank {r}"}}
        )
        events.append(
            {"ph": "M", "name": "thread_sort_index", "pid": 0, "tid": r,
             "args": {"sort_index": r}}
        )
        for rec in per_rank[r]:
            if rec.get("kind") != "span" or rec.get("t0") is None:
                continue
            ts_us = (rec["t0"] + offsets.get(r, 0.0) - base) * 1e6
            dur_us = max(0.0, float(rec.get("wall_s", 0.0))) * 1e6
            args = {
                k: v for k, v in rec.items()
                if k not in ("kind", "name", "path", "t0", "wall_s", "rank")
            }
            events.append(
                {"ph": "X", "cat": "span", "name": rec.get("path") or rec.get("name", "?"),
                 "pid": 0, "tid": r, "ts": ts_us, "dur": dur_us, "args": args}
            )
            if rec.get("name") == "rendezvous.allgather" and rec.get("round") is not None:
                flow_ends.setdefault(_round_key(rec), {})[r] = ts_us + dur_us

    # flow arrows: anchor rank's round exit -> every other participant's exit
    flow_id = 0
    for key in sorted(flow_ends, key=lambda k: min(flow_ends[k].values())):
        by_rank = flow_ends[key]
        if len(by_rank) < 2:
            continue
        anchor = min(by_rank)
        flow_id += 1
        name = f"rendezvous round {key[-1]}"
        events.append(
            {"ph": "s", "cat": "rendezvous", "name": name, "id": flow_id,
             "pid": 0, "tid": anchor, "ts": by_rank[anchor]}
        )
        for r, ts in sorted(by_rank.items()):
            if r == anchor:
                continue
            events.append(
                {"ph": "f", "bp": "e", "cat": "rendezvous", "name": name,
                 "id": flow_id, "pid": 0, "tid": r, "ts": ts}
            )
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "generator": "spark_rapids_ml_tpu.diagnostics.merge_chrome_trace",
            "trace_id": trace_id,
            "ranks": sorted(per_rank),
            "clock_offsets_s": {str(r): o for r, o in offsets.items()},
        },
    }


def chrome_trace_from_files(
    base_path: str, *, trace_id: Optional[str] = None, align_clocks: bool = True
) -> Dict[str, Any]:
    """`load_telemetry_jsonl` + `merge_chrome_trace` in one call (what the
    `benchmark/trace_merge.py` CLI wraps)."""
    return merge_chrome_trace(
        load_telemetry_jsonl(base_path), trace_id=trace_id, align_clocks=align_clocks
    )
