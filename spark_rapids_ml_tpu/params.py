#
# Parameter system for the TPU-native framework.
#
# Two halves, mirroring the reference's L6 param-translation layer
# (/root/reference/python/src/spark_rapids_ml/params.py):
#
#  1. A Spark-ML-compatible `Param`/`Params` implementation (pyspark is an optional
#     dependency in this build, so the Param surface — set/getOrDefault/copy/
#     explainParams and the `Has*` shared-param mixins — lives in-tree). User code
#     written against `pyspark.ml` setters (`setK`, `setInputCol`, ...) works
#     unchanged against these classes.
#
#  2. The declarative Spark-param -> solver-kwarg mapping machinery:
#     `_TpuClass._param_mapping` / `_param_value_mapping` /
#     `_get_solver_params_default` (reference params.py:131-212) and
#     `_TpuParams.solver_params` / `num_workers` / `_set_params`
#     (reference params.py:215-361). A `None`-mapped Spark param is unsupported
#     (raises on set); an ``""``-mapped one is accepted and silently dropped.
#
from __future__ import annotations

import uuid
from abc import ABC, abstractmethod
from typing import Any, Callable, Dict, List, Mapping, Optional, TypeVar, Union

__all__ = [
    "Param",
    "Params",
    "P",
    "HasInputCol",
    "HasInputCols",
    "HasOutputCol",
    "HasOutputCols",
    "HasFeaturesCol",
    "HasFeaturesCols",
    "HasLabelCol",
    "HasPredictionCol",
    "HasProbabilityCol",
    "HasRawPredictionCol",
    "HasWeightCol",
    "HasIDCol",
    "HasTol",
    "HasMaxIter",
    "HasRegParam",
    "HasElasticNetParam",
    "HasFitIntercept",
    "HasStandardization",
    "HasSeed",
    "HasEnableSparseDataOptim",
    "_TpuClass",
    "_TpuParams",
]

P = TypeVar("P", bound="Params")


class Param:
    """A named parameter with documentation and an optional type converter.

    Unlike pyspark, `Param` objects here are class attributes declared once per
    mixin/class; the owning instance is resolved at access time, which keeps
    `copy()` trivial (no per-instance param rebinding needed).
    """

    def __init__(self, name: str, doc: str, typeConverter: Optional[Callable[[Any], Any]] = None):
        self.name = name
        self.doc = doc
        self.typeConverter = typeConverter

    def __repr__(self) -> str:
        return f"Param(name={self.name!r}, doc={self.doc!r})"

    def __hash__(self) -> int:
        return hash(self.name)

    def __eq__(self, other) -> bool:
        return isinstance(other, Param) and self.name == other.name


class TypeConverters:
    """Subset of pyspark.ml.param.TypeConverters used by this framework."""

    @staticmethod
    def toInt(v) -> int:
        return int(v)

    @staticmethod
    def toFloat(v) -> float:
        return float(v)

    @staticmethod
    def toBoolean(v) -> bool:
        if isinstance(v, bool):
            return v
        raise TypeError(f"Boolean Param requires value of type bool, got {type(v)}")

    @staticmethod
    def toString(v) -> str:
        return str(v)

    @staticmethod
    def toListString(v) -> List[str]:
        return [str(x) for x in v]

    @staticmethod
    def toListFloat(v) -> List[float]:
        return [float(x) for x in v]

    @staticmethod
    def identity(v):
        return v


class Params:
    """Base class holding user-set and default parameter maps.

    Implements the pyspark `Params` surface consumed by the reference framework
    and its tests: ``hasParam``, ``getParam``, ``isSet``, ``isDefined``,
    ``getOrDefault``, ``set``, ``extractParamMap``, ``copy``, ``explainParams``.
    """

    def __init__(self) -> None:
        self._paramMap: Dict[Param, Any] = {}
        self._defaultParamMap: Dict[Param, Any] = {}
        self.uid = f"{type(self).__name__}_{uuid.uuid4().hex[:12]}"

    # -- param discovery -------------------------------------------------
    @property
    def params(self) -> List[Param]:
        """All Param class attributes of this instance, sorted by name."""
        seen: Dict[str, Param] = {}
        for klass in type(self).__mro__:
            for name, attr in vars(klass).items():
                if isinstance(attr, Param) and attr.name not in seen:
                    seen[attr.name] = attr
        return [seen[k] for k in sorted(seen)]

    def hasParam(self, paramName: str) -> bool:
        return any(p.name == paramName for p in self.params)

    def getParam(self, paramName: str) -> Param:
        for p in self.params:
            if p.name == paramName:
                return p
        raise AttributeError(f"{type(self).__name__} has no param {paramName!r}")

    def _resolveParam(self, param: Union[str, Param]) -> Param:
        return self.getParam(param) if isinstance(param, str) else self.getParam(param.name)

    # -- get/set ---------------------------------------------------------
    def isSet(self, param: Union[str, Param]) -> bool:
        return self._resolveParam(param) in self._paramMap

    def hasDefault(self, param: Union[str, Param]) -> bool:
        return self._resolveParam(param) in self._defaultParamMap

    def isDefined(self, param: Union[str, Param]) -> bool:
        return self.isSet(param) or self.hasDefault(param)

    def getOrDefault(self, param: Union[str, Param]):
        param = self._resolveParam(param)
        if param in self._paramMap:
            return self._paramMap[param]
        return self._defaultParamMap[param]

    def set(self: P, param: Union[str, Param], value: Any) -> P:
        param = self._resolveParam(param)
        if param.typeConverter is not None and value is not None:
            value = param.typeConverter(value)
        self._paramMap[param] = value
        return self

    def _set(self: P, **kwargs: Any) -> P:
        for name, value in kwargs.items():
            self.set(name, value)
        return self

    def _setDefault(self: P, **kwargs: Any) -> P:
        for name, value in kwargs.items():
            self._defaultParamMap[self.getParam(name)] = value
        return self

    def clear(self, param: Union[str, Param]) -> None:
        self._paramMap.pop(self._resolveParam(param), None)

    def extractParamMap(self, extra: Optional[Mapping[Param, Any]] = None) -> Dict[Param, Any]:
        paramMap = dict(self._defaultParamMap)
        paramMap.update(self._paramMap)
        if extra:
            paramMap.update(extra)
        return paramMap

    def explainParam(self, param: Union[str, Param]) -> str:
        param = self._resolveParam(param)
        values = []
        if self.hasDefault(param):
            values.append(f"default: {self._defaultParamMap[param]}")
        if self.isSet(param):
            values.append(f"current: {self._paramMap[param]}")
        return f"{param.name}: {param.doc} ({', '.join(values) if values else 'undefined'})"

    def explainParams(self) -> str:
        return "\n".join(self.explainParam(p) for p in self.params)

    # -- copy ------------------------------------------------------------
    def copy(self: P, extra: Optional[Mapping[Param, Any]] = None) -> P:
        import copy as _copy

        that = _copy.copy(self)
        that._paramMap = dict(self._paramMap)
        that._defaultParamMap = dict(self._defaultParamMap)
        if extra:
            for param, value in extra.items():
                that.set(param, value)
        return that

    def _copyValues(self, to: "Params", extra: Optional[Mapping[Param, Any]] = None) -> "Params":
        paramMap = dict(self._paramMap)
        if extra:
            paramMap.update(extra)
        for param, value in self._defaultParamMap.items():
            if to.hasParam(param.name):
                to._defaultParamMap[to.getParam(param.name)] = value
        for param, value in paramMap.items():
            if to.hasParam(param.name):
                to._paramMap[to.getParam(param.name)] = value
        return to


# ---------------------------------------------------------------------------
# Shared-param mixins (pyspark.ml.param.shared equivalents + reference extras)
# ---------------------------------------------------------------------------


def _mixin(name: str, doc: str, conv, default=None, has_default: bool = True):
    """Build a HasX mixin class with a getX getter (setters live on estimators)."""
    param = Param(name, doc, conv)
    cap = name[0].upper() + name[1:]

    def getter(self):
        return self.getOrDefault(name)

    body: Dict[str, Any] = {name: param, f"get{cap}": getter}

    def __init__(self, *args, **kwargs):  # noqa: N807  (cooperative MRO chain)
        super(cls, self).__init__(*args, **kwargs)
        if has_default:
            self._setDefault(**{name: default})

    body["__init__"] = __init__
    cls = type(f"Has{cap}", (Params,), body)
    return cls


HasInputCol = _mixin("inputCol", "input column name", TypeConverters.toString, has_default=False)
HasInputCols = _mixin("inputCols", "input column names", TypeConverters.toListString, has_default=False)
HasOutputCol = _mixin("outputCol", "output column name", TypeConverters.toString, has_default=False)
HasOutputCols = _mixin("outputCols", "output column names", TypeConverters.toListString, has_default=False)
HasFeaturesCol = _mixin("featuresCol", "features column name", TypeConverters.toString, default="features")
HasLabelCol = _mixin("labelCol", "label column name", TypeConverters.toString, default="label")
HasPredictionCol = _mixin("predictionCol", "prediction column name", TypeConverters.toString, default="prediction")
HasProbabilityCol = _mixin(
    "probabilityCol", "column for predicted class conditional probabilities", TypeConverters.toString, default="probability"
)
HasRawPredictionCol = _mixin(
    "rawPredictionCol", "raw prediction (confidence) column name", TypeConverters.toString, default="rawPrediction"
)
HasWeightCol = _mixin("weightCol", "weight column name", TypeConverters.toString, has_default=False)
HasTol = _mixin("tol", "convergence tolerance for iterative algorithms", TypeConverters.toFloat, default=1e-6)
HasMaxIter = _mixin("maxIter", "max number of iterations (>= 0)", TypeConverters.toInt, default=100)
HasRegParam = _mixin("regParam", "regularization parameter (>= 0)", TypeConverters.toFloat, default=0.0)
HasElasticNetParam = _mixin(
    "elasticNetParam", "ElasticNet mixing parameter in [0, 1]; 0=L2, 1=L1", TypeConverters.toFloat, default=0.0
)
HasFitIntercept = _mixin("fitIntercept", "whether to fit an intercept term", TypeConverters.toBoolean, default=True)
HasStandardization = _mixin(
    "standardization", "whether to standardize the training features before fitting", TypeConverters.toBoolean, default=True
)
HasSeed = _mixin("seed", "random seed", TypeConverters.toInt, default=0)


class HasFeaturesCols(Params):
    """Param for a *list* of scalar feature columns (reference params.py:68-88)."""

    featuresCols = Param(
        "featuresCols",
        "features column names for multi-column scalar input",
        TypeConverters.toListString,
    )

    def getFeaturesCols(self) -> List[str]:
        return self.getOrDefault("featuresCols")

    def setFeaturesCols(self: P, value: List[str]) -> P:
        return self._set_params(featuresCols=value)


class HasIDCol(Params):
    """Param for a row-id column used to join results back (reference params.py:90-110)."""

    idCol = Param("idCol", "id column name for joining results back to input rows", TypeConverters.toString)

    def getIdCol(self) -> str:
        return self.getOrDefault("idCol")

    def setIdCol(self: P, value: str) -> P:
        return self._set_params(idCol=value)


class HasEnableSparseDataOptim(Params):
    """Opt-in CSR ingest path (reference params.py:44-65)."""

    enable_sparse_data_optim = Param(
        "enable_sparse_data_optim",
        "If None (default) autodetect sparse input; True forces CSR ingest; False forces dense.",
        TypeConverters.identity,
    )

    def __init__(self, *args, **kwargs) -> None:
        super().__init__(*args, **kwargs)
        self._setDefault(enable_sparse_data_optim=None)


# ---------------------------------------------------------------------------
# Spark-param <-> solver-kwarg translation (reference _CumlClass/_CumlParams)
# ---------------------------------------------------------------------------


class _TpuClass(ABC):
    """Declarative mapping from Spark ML param names/values to TPU-solver kwargs.

    Mirrors ``_CumlClass`` (reference params.py:131-212): subclasses declare a
    mapping table instead of writing translation code. A value of ``None`` marks
    the Spark param unsupported (raises when set); ``""`` marks it accepted but
    ignored (not forwarded to the solver).
    """

    @classmethod
    def _param_mapping(cls) -> Dict[str, Optional[str]]:
        return {}

    @classmethod
    def _param_value_mapping(cls) -> Dict[str, Callable[[Any], Union[None, Any]]]:
        """Per-solver-kwarg value translators, e.g. Spark 'euclidean' -> 'l2'."""
        return {}

    @abstractmethod
    def _get_solver_params_default(self) -> Dict[str, Any]:
        """Default solver kwargs (and the set of allowed direct solver params)."""
        raise NotImplementedError


class _TpuParams(_TpuClass, Params):
    """Param-sync layer: keeps `solver_params` consistent with Spark Params.

    Mirrors ``_CumlParams`` (reference params.py:215-361). Constructor-only
    extras carried over from the reference: ``num_workers`` (here: number of mesh
    devices / processes used for fit) and ``float32_inputs``.
    """

    _float32_inputs: bool = True

    def __init__(self) -> None:
        super().__init__()
        self._solver_params: Dict[str, Any] = self._get_solver_params_default()
        self._num_workers: Optional[int] = None
        self._float32_inputs = True

    # -- solver params ----------------------------------------------------
    @property
    def solver_params(self) -> Dict[str, Any]:
        return self._solver_params

    # Drop-in alias for code written against the reference's attribute name.
    @property
    def cuml_params(self) -> Dict[str, Any]:
        return self._solver_params

    def _set_solver_param(self, name: str, value: Any, silent: bool = False) -> None:
        value_mapping = self._param_value_mapping()
        if name in value_mapping:
            mapped = value_mapping[name](value)
            if mapped is None and value is not None:
                raise ValueError(f"Value {value!r} for parameter {name!r} is not supported by the TPU solver")
            value = mapped
        if name not in self._solver_params and not silent:
            raise ValueError(f"Unknown solver parameter {name!r} for {type(self).__name__}")
        self._solver_params[name] = value

    # -- num_workers ------------------------------------------------------
    @property
    def num_workers(self) -> int:
        return self._num_workers if self._num_workers is not None else self._infer_num_workers()

    @num_workers.setter
    def num_workers(self, value: int) -> None:
        if value is not None and value < 1:
            raise ValueError("num_workers must be >= 1")
        self._num_workers = value

    def _infer_num_workers(self) -> int:
        """Infer parallelism from the visible accelerator devices.

        The reference infers one worker per cluster GPU (params.py:430-500); here
        a worker is one mesh device (chip), so local device count is the default.
        """
        try:
            from .parallel.mesh import default_devices

            return max(1, len(default_devices()))
        except Exception:  # pragma: no cover - jax is a hard dep in practice
            return 1

    @property
    def float32_inputs(self) -> bool:
        return self._float32_inputs

    def _setDefault(self: P, **kwargs: Any) -> P:
        """Also push mapped Spark-param defaults into solver params so the two
        tiers never disagree (a Spark default of regParam=0.0 must beat a
        solver-kwarg default of alpha=1e-4)."""
        super()._setDefault(**kwargs)
        param_map = self._param_mapping()
        for name, value in kwargs.items():
            mapped = param_map.get(name)
            if mapped:  # skip None (unsupported) and "" (dropped)
                try:
                    self._set_solver_param(mapped, value, silent=True)
                except ValueError:
                    pass  # a default value outside the solver's domain stays solver-side
        return self

    # -- the single entry point every setter funnels through --------------
    def _set_params(self: P, **kwargs: Any) -> P:
        """Route kwargs to Spark Params and/or solver params (reference params.py:304-358)."""
        param_map = self._param_mapping()
        for name, value in kwargs.items():
            if name == "num_workers":
                self.num_workers = value
                continue
            if name == "float32_inputs":
                self._float32_inputs = bool(value)
                continue
            if self.hasParam(name):
                self.set(name, value)
                if name in param_map:
                    mapped = param_map[name]
                    if mapped is None:
                        raise ValueError(
                            f"Spark ML param {name!r} is not supported by {type(self).__name__} on TPU"
                        )
                    if mapped != "":
                        self._set_solver_param(mapped, value, silent=True)
            elif name in self._solver_params:
                self._set_solver_param(name, value)
            else:
                raise ValueError(f"Unknown parameter {name!r} for {type(self).__name__}")
        return self

    def copy(self: P, extra: Optional[Mapping[Param, Any]] = None) -> P:
        that = super().copy(extra)
        that._solver_params = dict(self._solver_params)
        # re-sync mapped spark-param overrides into the copied solver params
        if extra:
            mapping = self._param_mapping()
            for param, value in extra.items():
                name = param.name if isinstance(param, Param) else param
                mapped = mapping.get(name)
                if mapped:
                    that._set_solver_param(mapped, value, silent=True)
        return that

    def _copy_solver_params(self: P, to: "_TpuParams") -> "_TpuParams":
        to._solver_params = dict(self._solver_params)
        to._num_workers = self._num_workers
        to._float32_inputs = self._float32_inputs
        return to

    # -- input-column resolution (reference params.py:395-428) -------------
    def _get_input_columns(self) -> tuple:
        """Returns (single_col_name, multi_col_names) — exactly one is non-None."""
        input_col, input_cols = None, None
        if self.hasParam("inputCol") and self.isDefined("inputCol"):
            input_col = self.getOrDefault("inputCol")
        elif self.hasParam("inputCols") and self.isDefined("inputCols"):
            input_cols = self.getOrDefault("inputCols")
        elif self.hasParam("featuresCol") and self.isSet("featuresCol"):
            input_col = self.getOrDefault("featuresCol")
        elif self.hasParam("featuresCols") and self.isDefined("featuresCols"):
            input_cols = self.getOrDefault("featuresCols")
        elif self.hasParam("featuresCol") and self.hasDefault("featuresCol"):
            input_col = self.getOrDefault("featuresCol")
        if input_col is None and input_cols is None:
            raise ValueError("Input column(s) must be set via setInputCol(s)/setFeaturesCol(s)")
        return input_col, input_cols
