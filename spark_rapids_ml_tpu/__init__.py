#
# spark_rapids_ml_tpu: a TPU-native distributed classical-ML framework with the
# API surface and capabilities of spark-rapids-ml (reference at /root/reference),
# built on JAX/XLA: solvers are pure-XLA SPMD programs over a
# `jax.sharding.Mesh` with explicit collectives, data lives as row-sharded
# HBM-resident `jax.Array`s, and the hot inner loops are expressed as large
# static-shape batched matmuls/reductions that XLA tiles onto the MXU —
# measured faster than hand-written kernels for every solver profiled so far.
#
__version__ = "0.1.0"

from .errors import (  # noqa: F401
    HbmBudgetError,
    IngestValidationError,
    NumericsError,
    PreemptedError,
    RankFailedError,
    RendezvousTimeoutError,
    RequestTimeoutError,
    SchedulerSaturatedError,
    ServeOverloadError,
    ServingStoppedError,
    SolverDivergedError,
    SrmlError,
)
from .linalg import DenseVector, SparseVector, Vectors  # noqa: F401


def device_dataset_scope():
    """Re-export of `core.device_dataset_scope` — enable DeviceDataset reuse
    (one ingest+layout for every fit over the same dataset inside the scope;
    docs/performance.md "Multi-fit engine")."""
    from .core import device_dataset_scope as _scope

    return _scope()


def __getattr__(name):
    """Lazy re-exports (PEP 562): `scheduler.FitScheduler` — the
    multi-tenant fit queue (priority submit, bin-packed co-admission,
    checkpoint preemption over the shared HBM ledger; docs/scheduling.md) —
    and the `ops_plane` package (rolling-window exporters, SLO monitors,
    decision audit trail; docs/observability.md "Ops plane"). The REAL
    objects are returned, so isinstance/subclass/positional construction
    behave identically to the deep imports."""
    if name == "FitScheduler":
        from .scheduler import FitScheduler

        return FitScheduler
    if name == "ops_plane":
        # importlib, not `from . import`: the from-import falls back to THIS
        # __getattr__ while the submodule is still unset — infinite recursion
        import importlib

        return importlib.import_module(".ops_plane", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "DenseVector",
    "SparseVector",
    "Vectors",
    "SrmlError",
    "RankFailedError",
    "RendezvousTimeoutError",
    "SolverDivergedError",
    "IngestValidationError",
    "HbmBudgetError",
    "NumericsError",
    "PreemptedError",
    "SchedulerSaturatedError",
    "RequestTimeoutError",
    "ServeOverloadError",
    "ServingStoppedError",
    "device_dataset_scope",
    "FitScheduler",
    "ops_plane",
    "__version__",
]


def _lazy_imports():  # populated as model families land
    pass
