#
# spark_rapids_ml_tpu: a TPU-native distributed classical-ML framework with the
# API surface and capabilities of spark-rapids-ml (reference at /root/reference),
# built on JAX/XLA: solvers are pure-XLA SPMD programs over a
# `jax.sharding.Mesh` with explicit collectives, data lives as row-sharded
# HBM-resident `jax.Array`s, and the hot inner loops are expressed as large
# static-shape batched matmuls/reductions that XLA tiles onto the MXU —
# measured faster than hand-written kernels for every solver profiled so far.
#
__version__ = "0.1.0"

from .errors import (  # noqa: F401
    HbmBudgetError,
    IngestValidationError,
    RankFailedError,
    RendezvousTimeoutError,
    SolverDivergedError,
    SrmlError,
)
from .linalg import DenseVector, SparseVector, Vectors  # noqa: F401


def device_dataset_scope():
    """Re-export of `core.device_dataset_scope` — enable DeviceDataset reuse
    (one ingest+layout for every fit over the same dataset inside the scope;
    docs/performance.md "Multi-fit engine")."""
    from .core import device_dataset_scope as _scope

    return _scope()


__all__ = [
    "DenseVector",
    "SparseVector",
    "Vectors",
    "SrmlError",
    "RankFailedError",
    "RendezvousTimeoutError",
    "SolverDivergedError",
    "IngestValidationError",
    "HbmBudgetError",
    "device_dataset_scope",
    "__version__",
]


def _lazy_imports():  # populated as model families land
    pass
