#
# Fault injection for the control plane — the test substrate that PROVES the
# resilience claims in docs/robustness.md instead of asserting them.
#
# A fault plan is a compact spec string (the `SRML_FAULT_PLAN` env var, or
# `set_fault_plan()` in-process): semicolon-separated entries, each
# `kind:key=value:key=value...`:
#
#   kill:rank=1:round=3            SIGKILL the process entering round 3 on
#                                  rank 1 — no abort file, no atexit: the
#                                  hard-death case heartbeats exist for
#   abort:rank=1:round=3           publish the abort sentinel then raise (the
#                                  graceful-failure case: an exception that
#                                  reaches TpuContext.__exit__)
#   delay:rank=0:round=2:seconds=0.5   sleep before joining the round
#   drop:rank=1:round=2            lose this rank's message: never join the
#                                  round, so every rank (dropper included)
#                                  raises the symmetric RendezvousTimeoutError
#   fail:stage=fit:times=1         raise a transient error at the START of a
#                                  retryable stage attempt (core.retryable_stage
#                                  consults `maybe_fail_stage`) — the injected
#                                  "transient rendezvous fault" of the
#                                  retry-to-bit-identical acceptance test
#   oom:budget=1048576             SHRINK the HBM budget: memory.admit_fit
#                                  consults `injected_hbm_budget()` and budgets
#                                  the next admission against this many bytes —
#                                  the fit-entry demotion ladder (RESIDENT ->
#                                  STREAM -> HbmBudgetError) testable without a
#                                  real TPU
#   burst:stage=serve:rows=4096:seconds=2   offered-load burst: the harness
#                                  driving the stage (the serving saturation
#                                  bench/tests) consults `maybe_burst_stage`
#                                  and, when an un-spent entry matches, ramps
#                                  offered load to `rows` rows/s for
#                                  `seconds` — the overload ladder's
#                                  healthy -> shed -> recover scenario
#                                  testable on CPU CI (docs/serving.md
#                                  "Overload & backpressure")
#   oom:stage=solve:round=2        simulated ALLOCATION FAILURE: raise a
#                                  RESOURCE_EXHAUSTED-shaped RuntimeError at
#                                  the named stage — `placement` fires before
#                                  layout (its round= index is the
#                                  retry/recovery ATTEMPT, so round=1 targets
#                                  the re-placement of a recovery attempt),
#                                  `solve` fires at solver checkpoint
#                                  boundaries (round= = the iteration) —
#                                  exercising the catch-convert-retry-
#                                  streaming path end to end. rank= restricts
#                                  either oom form to one process
#                                  (diagnostics process rank).
#
# Every entry fires at most `times` times (default 1), so a retried attempt
# runs clean — exactly the transient-fault shape the fit driver retries.
# `rank=` names the ORIGINAL rank identity (`Rendezvous.orig_rank`): after a
# membership reform renumbers survivors, the fault keeps targeting the same
# physical process, never whoever inherited its index.
#
from __future__ import annotations

import os
import signal
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..errors import RendezvousTimeoutError
from .context import Rendezvous

__all__ = [
    "Fault",
    "parse_fault_plan",
    "set_fault_plan",
    "clear_fault_plan",
    "active_plan",
    "maybe_fail_stage",
    "maybe_delay_stage",
    "maybe_burst_stage",
    "maybe_fail_oom",
    "injected_hbm_budget",
    "ChaosRendezvous",
]

_KINDS = {"kill", "abort", "delay", "drop", "fail", "oom", "burst"}


@dataclass
class Fault:
    kind: str  # kill | abort | delay | drop | fail
    rank: Optional[int] = None  # rendezvous faults: which rank misbehaves
    round: Optional[int] = None  # rendezvous faults: at which round index
    stage: Optional[str] = None  # `fail` faults: which retryable stage
    seconds: float = 0.0  # `delay` faults: how long
    reason: str = "chaos"  # `abort` faults: published reason
    times: int = 1  # how many firings remain
    # `kill` faults: a kill+rejoin recovery injection — the harness driving
    # the plan relaunches the victim, which rejoins the reformed group at
    # the epoch boundary (FileRendezvous.rejoin). Informational to the
    # in-process injector (the kill itself is identical); consumed by
    # subprocess harnesses (tests/chaos_worker.py, ci/chaos_smoke.py).
    respawn: int = 0
    # `oom` faults: injected per-device HBM budget in bytes (0 = this entry is
    # a simulated allocation failure at stage/round instead)
    budget: int = 0
    # `burst` faults: offered-load ramp in rows/second the consulting harness
    # drives at the named stage for `seconds`
    rows: int = 0
    fired: int = field(default=0)

    def spent(self) -> bool:
        return self.fired >= self.times


def parse_fault_plan(spec: str) -> List[Fault]:
    """Parse a plan spec; raises ValueError on malformed entries so a typo'd
    `SRML_FAULT_PLAN` fails loudly instead of silently injecting nothing."""
    faults: List[Fault] = []
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        kind = parts[0].strip()
        if kind not in _KINDS:
            raise ValueError(f"unknown fault kind {kind!r} in plan entry {entry!r}")
        kwargs: Dict[str, str] = {}
        for kv in parts[1:]:
            k, sep, v = kv.partition("=")
            if not sep:
                raise ValueError(f"malformed fault field {kv!r} in plan entry {entry!r}")
            kwargs[k.strip()] = v.strip()
        fault = Fault(kind=kind)
        for k, v in kwargs.items():
            if k == "rank":
                fault.rank = int(v)
            elif k == "round":
                fault.round = int(v)
            elif k == "stage":
                fault.stage = v
            elif k == "seconds":
                fault.seconds = float(v)
            elif k == "reason":
                fault.reason = v
            elif k == "times":
                fault.times = int(v)
            elif k == "respawn":
                fault.respawn = int(v)
            elif k == "budget":
                fault.budget = int(v)
            elif k == "rows":
                fault.rows = int(v)
            else:
                raise ValueError(f"unknown fault field {k!r} in plan entry {entry!r}")
        if fault.kind == "fail":
            if fault.stage is None:
                raise ValueError(f"fail fault needs stage=<name>: {entry!r}")
        elif fault.kind == "oom":
            if fault.budget <= 0 and fault.stage is None:
                raise ValueError(
                    f"oom fault needs budget=<bytes> or stage=<name>: {entry!r}"
                )
        elif fault.kind == "burst":
            # offered-load burst at an instrumented stage (the serving
            # saturation scenario): all three fields are load-shape, so all
            # three are required — a burst with no rows or no duration is a
            # typo, not a plan
            if fault.stage is None or fault.rows <= 0 or fault.seconds <= 0:
                raise ValueError(
                    f"burst fault needs stage=<name>, rows=<rows/s> and "
                    f"seconds=<s>: {entry!r}"
                )
        elif fault.kind == "delay" and fault.stage is not None:
            # stage-scoped latency injection (`delay:stage=serve:seconds=`):
            # consulted by maybe_delay_stage at instrumented stages (the
            # serving dispatch) — no rendezvous round involved
            if fault.seconds <= 0:
                raise ValueError(f"delay:stage= fault needs seconds=<s>: {entry!r}")
        elif fault.rank is None or fault.round is None:
            raise ValueError(f"{fault.kind} fault needs rank= and round=: {entry!r}")
        faults.append(fault)
    return faults


# The process-level plan: loaded once from SRML_FAULT_PLAN (so subprocess
# harness ranks inherit it through the environment), overridable in-process
# for tests. Firing state lives on the Fault objects — `times` is per-process.
_PLAN: Optional[List[Fault]] = None
_PLAN_LOADED = False


def active_plan() -> List[Fault]:
    global _PLAN, _PLAN_LOADED
    if not _PLAN_LOADED:
        spec = os.environ.get("SRML_FAULT_PLAN", "")
        _PLAN = parse_fault_plan(spec) if spec else []
        _PLAN_LOADED = True
    return _PLAN or []


def set_fault_plan(spec: str) -> List[Fault]:
    """Install a plan in-process (tests); returns the parsed faults."""
    global _PLAN, _PLAN_LOADED
    _PLAN = parse_fault_plan(spec)
    _PLAN_LOADED = True
    return _PLAN


def clear_fault_plan() -> None:
    global _PLAN, _PLAN_LOADED
    _PLAN = []
    _PLAN_LOADED = True


def _rank_matches(f: Fault) -> bool:
    """`rank=`-restricted oom faults fire only on the named process (the
    diagnostics process rank — TpuContext rank or SRML_RANK/set_process_rank
    where no context exists). An unset rank matches every process."""
    if f.rank is None:
        return True
    from .. import diagnostics

    return diagnostics._rank() == f.rank


def injected_hbm_budget() -> Optional[int]:
    """The shrunken per-device HBM budget injected by an un-spent
    `oom:budget=<bytes>` fault, consuming one firing — or None. Consulted by
    `memory.device_capacity_bytes` ahead of every other capacity source, so a
    plan entry demotes exactly `times` admissions."""
    from .. import diagnostics

    for f in active_plan():
        if f.kind == "oom" and f.budget > 0 and not f.spent() and _rank_matches(f):
            f.fired += 1
            diagnostics.record_event(
                "chaos_injection", fault="oom", budget=f.budget
            )
            return f.budget
    return None


def maybe_fail_oom(stage: str, index: int = 0) -> None:
    """Simulated allocation failure: an un-spent `oom:stage=<s>` fault whose
    `round=` (when set) matches `index` raises a RESOURCE_EXHAUSTED-shaped
    RuntimeError — indistinguishable to `memory.is_oom_error` from a real
    backend OOM, so the catch-convert-retry-streaming ladder is exercised end
    to end. Call sites: core layout (`placement`, index 0) and the solver
    checkpoint boundaries (`solve`, index = iteration)."""
    from .. import diagnostics

    for f in active_plan():
        if (
            f.kind != "oom"
            or f.budget > 0
            or f.stage != stage
            or f.spent()
            or (f.round is not None and f.round != index)
            or not _rank_matches(f)
        ):
            continue
        f.fired += 1
        diagnostics.record_event(
            "chaos_injection", fault="oom", stage=stage, index=index
        )
        raise RuntimeError(
            f"RESOURCE_EXHAUSTED: chaos injected allocation failure at stage "
            f"{stage!r} (index {index})"
        )


def maybe_delay_stage(stage: str) -> None:
    """Stage-scoped latency injection: an un-spent `delay:stage=<s>` fault
    sleeps `seconds` before the stage runs, consuming one firing — the
    chaos-driven latency spike the ops plane's SLO burn-rate acceptance test
    injects into the serving dispatch (docs/observability.md "Ops plane")."""
    from .. import diagnostics

    for f in active_plan():
        if (
            f.kind != "delay"
            or f.stage != stage
            or f.spent()
            or not _rank_matches(f)
        ):
            continue
        f.fired += 1
        diagnostics.record_event(
            "chaos_injection", fault="delay", stage=stage, seconds=f.seconds
        )
        time.sleep(f.seconds)  # sleep-ok: plan-bounded injected stage delay


def maybe_burst_stage(stage: str) -> Optional[Fault]:
    """Offered-load burst injection: an un-spent `burst:stage=<s>` fault
    matching `stage` is consumed (one firing) and returned — the consulting
    harness (the serving saturation bench/tests) then ramps offered load to
    `fault.rows` rows/second for `fault.seconds`. None when no entry
    matches. Unlike the other stage hooks this one injects nothing itself:
    the BURST is caller-generated traffic, so the fault entry is the load
    shape, and the chaos plan stays the single place a scenario's faults
    are declared (docs/serving.md "Overload & backpressure")."""
    from .. import diagnostics

    for f in active_plan():
        if (
            f.kind != "burst"
            or f.stage != stage
            or f.spent()
            or not _rank_matches(f)
        ):
            continue
        f.fired += 1
        diagnostics.record_event(
            "chaos_injection", fault="burst", stage=stage,
            rows=f.rows, seconds=f.seconds,
        )
        return f
    return None


def maybe_fail_stage(stage: str, attempt: int) -> None:
    """Hook consulted by `core.retryable_stage` at the start of every attempt:
    a matching un-spent `fail` fault raises a transient RendezvousTimeoutError
    (the retryable class), consuming one firing."""
    from .. import diagnostics

    for f in active_plan():
        if f.kind == "fail" and f.stage == stage and not f.spent():
            f.fired += 1
            diagnostics.record_event(
                "chaos_injection", fault="fail", stage=stage, attempt=attempt
            )
            raise RendezvousTimeoutError(
                f"chaos: injected transient failure at stage {stage!r} attempt {attempt}",
                timeout_s=0.0,
            )


class ChaosRendezvous(Rendezvous):
    """Wrapper that applies the active fault plan to an inner rendezvous.

    Tracks its own round counter (reset on `begin_epoch`, like the inner's);
    faults fire when (rank, round) match this wrapper's view of the round
    sequence — i.e. "the Nth control-plane round of this attempt"."""

    def __init__(self, inner: Rendezvous, plan: Optional[List[Fault]] = None):
        self.inner = inner
        self.rank = inner.rank
        self.nranks = inner.nranks
        self.plan = plan if plan is not None else active_plan()
        self._round = 0
        self._epoch = 0  # mirrors inner: base allgather tags records with it

    def _apply_faults(self, round_index: int) -> None:
        from .. import diagnostics

        for f in self.plan:
            # rank= targets the ORIGINAL rank identity, stable across
            # reforms. Matching the CURRENT index re-targets the fault onto
            # an innocent survivor after renumbering: kill rank=1, reform to
            # [0, 2], and the orig-2 survivor (now current rank 1, its own
            # per-process firing ledger still unspent) kills itself at the
            # same round of the recovery attempt — a second loss that
            # exhausts the budget (found by the kill-at-every-round sweep).
            if (
                f.kind in ("fail", "oom", "burst")  # stage/budget hooks, not rdv rounds
                or f.spent()
                or f.rank != self.orig_rank
                or f.round != round_index
            ):
                continue
            f.fired += 1
            # the injection itself is flight-recorder evidence: a post-mortem
            # of a chaos run shows WHERE the fault plan fired, not just its
            # downstream symptoms (for `kill` this event only survives in
            # SURVIVOR dumps if it was gossiped — the victim's ring dies with
            # it, which is exactly the hard-death shape being simulated)
            diagnostics.record_event(
                "chaos_injection", fault=f.kind, round=round_index,
                seconds=f.seconds if f.kind == "delay" else None,
            )
            if f.kind == "delay":
                time.sleep(f.seconds)  # sleep-ok: plan-bounded injected delay
            elif f.kind == "abort":
                self.inner.abort(f.reason)
                raise RuntimeError(
                    f"chaos: rank {self.rank} aborted at round {round_index} ({f.reason})"
                )
            elif f.kind == "drop":
                # the message is "lost": never join the round; wait out our
                # own deadline so the failure is the same symmetric timeout
                # the peers raise
                timeout_s = self.inner._round_timeout_s()
                time.sleep(timeout_s)  # sleep-ok: waits out OUR OWN round deadline (drop = symmetric timeout)
                self._raise_timeout(round_index, None, timeout_s)
            elif f.kind == "kill":
                # the hard-death case: no abort file, no atexit, no flush —
                # exactly what a preempted/OOM-killed TPU host looks like
                os.kill(os.getpid(), signal.SIGKILL)
                time.sleep(60)  # sleep-ok: SIGKILL already sent to self  # pragma: no cover - delivery race

    def _allgather_impl(self, payload: str) -> List[str]:
        round_index = self._round
        self._round += 1
        self._apply_faults(round_index)
        return self.inner._allgather_impl(payload)

    def abort(self, reason: str) -> None:
        self.inner.abort(reason)

    def begin_epoch(self, epoch: int) -> None:
        self.inner.begin_epoch(epoch)
        self._round = 0
        self._epoch = int(epoch)

    # -- elastic membership: the plan (and its fired state) RIDES the
    # recovery — a reformed group stays under chaos, so multi-fault plans
    # (kill, recover, kill again) exercise the bounded-losses path
    @property
    def can_reform(self) -> bool:
        return getattr(self.inner, "can_reform", False)

    @property
    def live_ranks(self):
        return self.inner.live_ranks

    @property
    def orig_rank(self):
        return self.inner.orig_rank

    @property
    def reform_generation(self):
        return getattr(self.inner, "reform_generation", 0)

    def reform(self, dead_ranks=(), generation: int = 1) -> "ChaosRendezvous":
        from .. import diagnostics

        new_inner = self.inner.reform(dead_ranks=dead_ranks, generation=generation)
        diagnostics.record_event(
            "chaos_reform", generation=int(generation),
            survivors=list(getattr(new_inner, "live_ranks", [])),
        )
        wrapped = ChaosRendezvous(new_inner, self.plan)
        wrapped.rank, wrapped.nranks = new_inner.rank, new_inner.nranks
        return wrapped

    def rejoin(self, generation=None) -> "ChaosRendezvous":
        new_inner = self.inner.rejoin(generation)
        wrapped = ChaosRendezvous(new_inner, self.plan)
        wrapped.rank, wrapped.nranks = new_inner.rank, new_inner.nranks
        return wrapped

    def close(self) -> None:
        self.inner.close()

    # the one-round override must land on the INNER instance: its
    # _allgather_impl reads its own attribute (base barrier() routes through
    # these hooks)
    def _get_timeout_override(self) -> Optional[float]:
        return self.inner._get_timeout_override()

    def _set_timeout_override(self, value: Optional[float]) -> None:
        self.inner._set_timeout_override(value)

    def _round_timeout_s(self) -> float:
        return self.inner._round_timeout_s()
