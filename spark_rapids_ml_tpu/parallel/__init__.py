#
# Parallel runtime: device mesh management, row-sharded global-array assembly,
# partition bookkeeping, and the distributed process-group context.
#
# This is the TPU-native replacement for the reference's L4 communicator stack
# (reference common/cuml_context.py NCCL/UCX clique + utils.py PartitionDescriptor):
# collectives are XLA `psum`/`all_gather`/`ppermute` over a `jax.sharding.Mesh`
# (ICI within a slice, DCN across), and the rendezvous/control plane is an
# `allgather`-of-strings abstraction that maps onto Spark's
# `BarrierTaskContext.allGather` when running under Spark, or a no-op in
# single-controller mode.
#
from .mesh import (  # noqa: F401
    DCN_AXIS,
    ROWS_AXIS,
    bucket_rows,
    bucket_size,
    build_mesh,
    chip_scope,
    current_chip_scope,
    default_devices,
    ensure_compilation_cache,
    get_mesh,
    make_global_rows,
    pad_rows,
    place_row_shards,
    place_rows,
    replicated,
    row_sharding,
    set_devices,
    shard_row_slices,
    submesh,
    survivor_mesh,
)
from .partition import PartitionDescriptor  # noqa: F401
from .context import (  # noqa: F401
    BarrierRendezvous,
    FileRendezvous,
    LocalRendezvous,
    Rendezvous,
    TpuContext,
    allgather_ndarray,
)
from .chaos import ChaosRendezvous  # noqa: F401
