#
# Distributed process-group context — the TPU-native replacement for the
# reference's `CumlContext` (reference common/cuml_context.py:36-167), which
# builds a NCCL clique (rank0 mints a uid, BarrierTaskContext.allGather
# broadcasts it, each rank nccl.init) plus an optional UCX endpoint mesh.
#
# On TPU there is no uid/endpoint plumbing: each worker process calls
# `jax.distributed.initialize(coordinator, num_processes, process_id)` and XLA
# compiles collectives onto ICI/DCN. What remains of the reference design is the
# *rendezvous pattern*: rank0 picks the coordinator endpoint and an
# allgather-of-strings control plane distributes it — exactly where the
# reference broadcasts the NCCL uid. Teardown mirrors destroy-on-success /
# abort-on-exception (cuml_context.py:150-167).
#
from __future__ import annotations

import contextlib
import json
import os
import re
import socket
import sys
import threading
import time
from typing import List, Optional, Tuple

from ..errors import RankFailedError, RendezvousTimeoutError
from ..utils import lockcheck

__all__ = [
    "Rendezvous",
    "LocalRendezvous",
    "FileRendezvous",
    "TpuContext",
    "allgather_ndarray",
    "ABORT_PREFIX",
]

# --------------------------------------------------------------------------
# Abort channel: a failing rank PUBLISHES its failure so survivors raise a
# typed RankFailedError within ~one heartbeat interval instead of blocking
# until (or past) the round deadline. The sentinel is a plain string so it
# travels over whatever substrate the rendezvous uses (slot write in
# LocalRendezvous, `abort_rank_<r>` file in FileRendezvous).
# --------------------------------------------------------------------------

ABORT_PREFIX = "ABORT:"

# A dead rank is declared failed when its heartbeat file is staler than
# MISS_FACTOR x heartbeat_interval_s: 1.5 gives half an interval of scheduler
# slack against false positives while keeping worst-case detection at
# 1.5 x interval after the last touch — inside the 2 x interval budget the
# fault-injection suite asserts.
_HEARTBEAT_MISS_FACTOR = 1.5

# FileRendezvous polls its round files every 5ms, but the failure scan (abort
# files + heartbeat mtimes — O(nranks) stat calls against a possibly-shared
# filesystem) runs at this coarser cadence: detection budgets are "promptly,
# well before the deadline", which ~50ms meets without a stat storm.
_FAILURE_SCAN_INTERVAL_S = 0.05


def format_abort(rank: int, reason: str) -> str:
    """``ABORT:<rank>:<reason>`` sentinel (reason newline-flattened)."""
    return f"{ABORT_PREFIX}{int(rank)}:{' '.join(str(reason).split())}"


def parse_abort(payload: str) -> Optional[Tuple[int, str]]:
    """(rank, reason) when `payload` is an abort sentinel, else None."""
    if not payload.startswith(ABORT_PREFIX):
        return None
    body = payload[len(ABORT_PREFIX):]
    rank_s, _, reason = body.partition(":")
    try:
        return int(rank_s), reason
    except ValueError:  # malformed — treat as unknown-rank abort
        return -1, body


def allgather_ndarray(rendezvous: "Rendezvous", arr, chunk_bytes: Optional[int] = None) -> List:
    """Allgather a host numpy array through the string control plane (base64 of
    the .npy encoding); returns the per-rank arrays in rank order. The analog of
    the reference's base64-over-BarrierTaskContext.allGather payloads
    (reference tree.py:343, knn.py:689-700).

    Large arrays are split into row chunks of at most `chunk_bytes` (default:
    the framework's ``config["broadcast_chunk_bytes"]`` — the reference's 8 GB
    broadcast-chunking knob, clustering.py:1013-1091) so no single control-plane
    round carries an unbounded payload."""
    import base64
    import io

    import numpy as np

    if chunk_bytes is None:
        from ..core import config

        chunk_bytes = int(config.get("broadcast_chunk_bytes", 8 << 30))

    arr = np.ascontiguousarray(arr)
    if arr.ndim == 0:  # scalars can't be row-chunked; one round carries them
        buf = io.BytesIO()
        np.save(buf, arr, allow_pickle=False)
        payloads = rendezvous.allgather(base64.b64encode(buf.getvalue()).decode("ascii"))
        return [
            np.load(io.BytesIO(base64.b64decode(p)), allow_pickle=False)
            for p in payloads
        ]
    row_bytes = max(1, arr[:1].nbytes)
    rows_per_chunk = max(1, chunk_bytes // row_bytes)
    n = arr.shape[0]
    n_chunks = max(1, -(-n // rows_per_chunk))
    # every rank must agree on the ROUND COUNT, not just its own chunking
    n_chunks = max(
        int(p) for p in rendezvous.allgather(str(n_chunks))
    )
    rows_per_chunk = max(1, -(-n // n_chunks))

    def ser(a):
        buf = io.BytesIO()
        np.save(buf, a, allow_pickle=False)
        return base64.b64encode(buf.getvalue()).decode("ascii")

    def de(p):
        return np.load(io.BytesIO(base64.b64decode(p)), allow_pickle=False)

    gathered_chunks: List[List] = []
    for c in range(n_chunks):
        part = arr[c * rows_per_chunk : (c + 1) * rows_per_chunk]
        gathered_chunks.append([de(p) for p in rendezvous.allgather(ser(part))])
    out = []
    for r in range(rendezvous.nranks):
        parts = [gathered_chunks[c][r] for c in range(n_chunks)]
        out.append(np.concatenate(parts, axis=0) if len(parts) > 1 else parts[0])
    return out


def allgather_concat(rendezvous: "Rendezvous", arr):
    """Gather every rank's row block and concatenate in rank order; returns
    ``(global_array, this_rank_row_offset)`` — the shared idiom behind the
    replicated-data strategies (DBSCAN full-set gather, ANN/kNN query
    replication, UMAP fit-sample union)."""
    import numpy as np

    blocks = allgather_ndarray(rendezvous, arr)
    offset = sum(len(b) for b in blocks[: rendezvous.rank])
    return np.concatenate(blocks, axis=0), offset


class Rendezvous:
    """Control-plane interface: allgather small strings + barrier.

    Implementations: `LocalRendezvous` (in-process threads, for tests and
    single-controller mode), and — when running under Spark barrier stages — a
    thin wrapper over `BarrierTaskContext` (see spark/integration module) whose
    `allGather` this API is shaped after.

    In-tree implementations provide `_allgather_impl`; the base `allgather`
    wraps it with telemetry (round-trip counter, payload bytes, latency
    histogram — rank-tagged, no collectives of its own). Out-of-tree
    subclasses overriding `allgather` directly keep working, minus telemetry.

    Failure contract (docs/robustness.md): every round is bounded by a
    deadline (``config["rendezvous_timeout_s"]`` unless the instance sets its
    own) and raises `RendezvousTimeoutError` when it elapses; a failing rank
    calls `abort(reason)` so survivors raise `RankFailedError` promptly
    instead of waiting the deadline out. `begin_epoch(n)` re-namespaces the
    round state so the fit driver's retries never read a failed attempt's
    stale rounds.
    """

    rank: int
    nranks: int

    # --- elastic membership (docs/robustness.md "Elastic recovery") -------
    # Whether this substrate can agree on a reduced live-rank set after a
    # peer dies (`reform`). Substrates with their own supervisor (Spark
    # barrier stages) leave this False: the stage fails and Spark relaunches.
    can_reform: bool = False
    # Original rank ids of the current membership, in current-rank order
    # (identity for a never-reformed group). `reform` results carry the
    # surviving subset so failures and post-mortems keep naming ORIGINAL
    # ranks across recovery epochs.
    _live_ranks: Optional[List[int]] = None
    reform_generation: int = 0

    @property
    def live_ranks(self) -> List[int]:
        return list(self._live_ranks) if self._live_ranks is not None else list(range(self.nranks))

    @property
    def orig_rank(self) -> int:
        return self.live_ranks[self.rank]

    def reform(self, dead_ranks=(), generation: int = 1) -> "Rendezvous":
        """Membership reform round: agree with the other live ranks on the
        surviving rank set (admitting any respawned rank that votes within
        the window) and return a NEW rendezvous over it — fresh namespace,
        ranks renumbered 0..len(live)-1, `live_ranks` mapping back to the
        original ids. `dead_ranks` (ORIGINAL ids) seeds the known-dead set;
        the protocol converges on votes + liveness beyond the hint."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support membership reform; "
            "rank failures stay terminal on this substrate"
        )

    def allgather(self, payload: str) -> List[str]:
        from .. import diagnostics, telemetry

        # round index + epoch are best-effort (in-tree impls track `_round`/
        # `_epoch`; a custom subclass without them still records, just
        # untagged) — they are what the flight recorder / trace merge
        # correlate lockstep rounds by. Epoch matters: `begin_epoch` resets
        # the round counter, so (epoch, round) is unique where round alone
        # collides across retry attempts.
        round_index = getattr(self, "_round", None)
        epoch = getattr(self, "_epoch", None)
        diagnostics.record_event(
            "rdv_enter", round=round_index, epoch=epoch, nranks=self.nranks
        )
        try:
            if not telemetry.enabled():
                out = self._allgather_impl(payload)
            else:
                t_enter = time.time()
                with telemetry.span(
                    "rendezvous.allgather",
                    nranks=self.nranks, round=round_index, epoch=epoch,
                ):
                    out = self._allgather_impl(payload)
                reg = telemetry.registry()
                reg.inc("rendezvous.rounds")
                reg.inc("rendezvous.payload_bytes", len(payload))
                # fleet-plane straggler stamps (sys.modules probe — the
                # control plane never pays the ops_plane import chain; a
                # process without the fleet plane records nothing). Entry +
                # exit wall-clock per (epoch, round) ride the next ops-round
                # payload so the merger can attribute cross-rank skew.
                fleet = sys.modules.get(
                    (__package__ or "spark_rapids_ml_tpu.parallel").rsplit(".", 1)[0]
                    + ".ops_plane.fleet"
                )
                if fleet is not None:
                    try:
                        fleet.note_round_exit(
                            self.rank, round_index, epoch, t_enter, time.time()
                        )
                    except Exception:  # pragma: no cover - stamps are best-effort
                        pass
        except BaseException as e:
            diagnostics.record_event(
                "rdv_fail", round=round_index, error=type(e).__name__
            )
            raise
        diagnostics.record_event("rdv_exit", round=round_index)
        return out

    def _allgather_impl(self, payload: str) -> List[str]:
        raise NotImplementedError

    def barrier(self, timeout_s: Optional[float] = None) -> None:
        """Barrier = empty-payload allgather. `timeout_s` overrides this one
        round's deadline (bounded teardown — TpuContext.__exit__)."""
        if timeout_s is None:
            self.allgather("")
            return
        prev = self._get_timeout_override()
        self._set_timeout_override(timeout_s)
        try:
            self.allgather("")
        finally:
            self._set_timeout_override(prev)

    # the override lives behind a hook pair so WRAPPERS (ChaosRendezvous, any
    # future decorator) can forward it to the inner instance whose
    # _allgather_impl actually reads it
    def _get_timeout_override(self) -> Optional[float]:
        return getattr(self, "_timeout_override", None)

    def _set_timeout_override(self, value: Optional[float]) -> None:
        self._timeout_override = value

    def abort(self, reason: str) -> None:
        """Publish this rank's failure so peers stop waiting. Default no-op:
        substrates with their own supervisor (Spark barrier stages fail the
        whole stage when a task dies) need no in-band abort channel."""

    def begin_epoch(self, epoch: int) -> None:
        """Re-namespace round state for retry attempt `epoch` (fit driver
        resync): implementations reset round counters and clear the previous
        epoch's abort markers so a coordinated retry starts clean."""

    def close(self) -> None:
        """Release background resources (heartbeat threads, file handles)."""

    def _round_timeout_s(self) -> float:
        """Effective per-round deadline: a one-round override (bounded
        teardown) > the instance's own timeout > the framework config knob."""
        override = getattr(self, "_timeout_override", None)
        if override is not None:
            return float(override)
        own = getattr(self, "timeout_s", None)
        if own is not None:
            return float(own)
        from ..core import config

        return float(config.get("rendezvous_timeout_s", 300.0))

    def _raise_rank_failed(self, rank: int, reason: str, round_index: Optional[int]) -> None:
        from .. import telemetry

        telemetry.registry().inc("rendezvous.rank_failures")
        raise RankFailedError(rank, reason, round_index=round_index)

    def _raise_timeout(
        self, round_index: int, missing: Optional[List[int]], timeout_s: float
    ) -> None:
        from .. import telemetry

        telemetry.registry().inc("rendezvous.timeouts")
        who = f"ranks {missing} " if missing else ""
        raise RendezvousTimeoutError(
            f"rendezvous round {round_index}: {who}missing after {timeout_s}s",
            round_index=round_index,
            missing_ranks=missing,
            timeout_s=timeout_s,
        )


class LocalRendezvous(Rendezvous):
    """Thread-barrier rendezvous for N ranks inside one process (test harness).

    The analog of running the reference's barrier stage in Spark local mode
    (tests/conftest.py:44-70 there): real collective code paths, one machine.
    """

    can_reform = True

    class _Shared:
        def __init__(self, nranks: int):
            self.barrier = threading.Barrier(nranks)
            self.slots: List[Optional[str]] = [None] * nranks
            self.lock = lockcheck.make_lock("parallel.context.LocalRendezvous._Shared.lock")
            self.abort_info: Optional[Tuple[int, str]] = None
            self.epoch = 0
            # generation -> (live original-rank list, the survivors' _Shared):
            # the FIRST reformer builds the entry; peers adopt it, so every
            # survivor agrees on one membership + one fresh barrier
            self.reforms: dict = {}

    def __init__(self, rank: int, shared: "_Shared", timeout_s: Optional[float] = None):
        self.rank = rank
        self.nranks = shared.barrier.parties
        self.timeout_s = timeout_s  # None -> config["rendezvous_timeout_s"]
        self._shared = shared
        self._round = 0
        self._epoch = 0

    @classmethod
    def create(cls, nranks: int, timeout_s: Optional[float] = None) -> List["LocalRendezvous"]:
        shared = cls._Shared(nranks)
        return [cls(r, shared, timeout_s) for r in range(nranks)]

    def reform(self, dead_ranks=(), generation: int = 1) -> "LocalRendezvous":
        """Thread-substrate membership reform: the first surviving rank to
        arrive computes the live set (current membership minus `dead_ranks`)
        and builds the survivors' fresh shared barrier; later arrivals adopt
        that entry, so all survivors agree by construction."""
        from .. import diagnostics, telemetry

        shared = self._shared
        generation = int(generation)
        with shared.lock:
            entry = shared.reforms.get(generation)
            if entry is None:
                dead = {int(r) for r in dead_ranks}
                live = [r for r in self.live_ranks if r not in dead]
                if not live:
                    raise RankFailedError(-1, "reform left no live ranks", round_index=None)
                entry = (live, LocalRendezvous._Shared(len(live)))
                shared.reforms[generation] = entry
        live, new_shared = entry
        if self.orig_rank not in live:
            raise RankFailedError(
                self.orig_rank, "this rank was declared dead by the reform round"
            )
        new = LocalRendezvous(live.index(self.orig_rank), new_shared, self.timeout_s)
        new._live_ranks = list(live)
        new.reform_generation = generation
        telemetry.registry().inc("rendezvous.reforms")
        diagnostics.record_event(
            "recovery_reform", generation=generation, survivors=list(live)
        )
        return new

    def abort(self, reason: str) -> None:
        """Publish ``ABORT:<rank>:<reason>`` (extra slot write) and break the
        barrier so every peer blocked in `barrier.wait` wakes immediately
        with a typed RankFailedError instead of its raw BrokenBarrierError."""
        from .. import diagnostics, telemetry

        shared = self._shared
        with shared.lock:
            if shared.abort_info is None:
                shared.abort_info = (self.rank, str(reason))
                cur = shared.slots[self.rank]
                if not (isinstance(cur, tuple) and cur[0] == self._epoch):
                    # leave a current-epoch payload in place: peers that
                    # completed the round's data barrier but have not yet
                    # copied the slots must still receive the full round (a
                    # rank dying BETWEEN rounds must not retroactively tear
                    # the round it finished); they learn of the abort from
                    # `abort_info` via the broken release fence instead
                    shared.slots[self.rank] = format_abort(self.rank, reason)
        telemetry.registry().inc("rendezvous.aborts_published")
        diagnostics.record_event("abort_published", reason=str(reason)[:200])
        diagnostics.flight_recorder().dump(reason="abort published")
        shared.barrier.abort()

    def begin_epoch(self, epoch: int) -> None:
        # idempotent per epoch: only the FIRST rank to request it performs the
        # barrier reset + state clear. A later rank repeating the reset would
        # break peers that already re-entered the new epoch's round 0 wait —
        # spuriously burning their bounded retry budget.
        shared = self._shared
        with shared.lock:
            if shared.epoch >= epoch > 0:
                # another rank already reset for this epoch — adopt it (the
                # slot tags compare against the INSTANCE epoch, so it must
                # advance on the idempotent path too)
                self._round = 0
                self._epoch = int(epoch)
                return
            shared.epoch = epoch
            shared.abort_info = None
            for i in range(self.nranks):
                shared.slots[i] = None
            # reset INSIDE the lock: no peer can observe the new epoch (and
            # re-enter round 0's wait) until the lock is released, so the
            # reset can never break a waiter of the epoch it is creating;
            # reset() does not block when nobody waits
            shared.barrier.reset()
        self._round = 0
        self._epoch = int(epoch)
        from .. import diagnostics

        diagnostics.record_event("epoch_begin", epoch=int(epoch))

    def _wait(self, round_index: int, timeout_s: float) -> None:
        """`barrier.wait` bounded by the round deadline; BrokenBarrierError
        (a peer aborted, a peer timed out, or WE timed out — `wait(timeout)`
        breaks the barrier for everyone) never leaks to callers: it converts
        to RankFailedError when an abort was published, else the symmetric
        RendezvousTimeoutError."""
        try:
            self._shared.barrier.wait(timeout=timeout_s)  # blocking-ok: deadline-bounded
        except threading.BrokenBarrierError:
            info = self._shared.abort_info
            if info is not None:
                self._raise_rank_failed(info[0], info[1], round_index)
            self._raise_timeout(round_index, None, timeout_s)

    def _allgather_impl(self, payload: str) -> List[str]:
        shared = self._shared
        round_index = self._round
        self._round += 1
        info = shared.abort_info
        if info is not None:  # a peer failed in an earlier round — fail fast
            self._raise_rank_failed(info[0], info[1], round_index)
        timeout_s = self._round_timeout_s()
        # slots carry an (epoch, round, payload) tag: a straggler still in a
        # FAILED epoch that only now reaches its old round must not silently
        # exchange payloads with a retried epoch's round on the same barrier —
        # the tag mismatch surfaces as the transient desync error below (the
        # file substrate gets the same protection from e<N>_round_<i> naming)
        shared.slots[self.rank] = (self._epoch, round_index, payload)  # type: ignore[assignment]
        self._wait(round_index, timeout_s)
        out_tagged = list(shared.slots)
        try:
            self._wait(round_index, timeout_s)  # don't let a fast rank overwrite slots early
        except (RankFailedError, RendezvousTimeoutError):
            # The first wait tripped, so every rank published this round and
            # our copy above is the complete exchange; only the RELEASE FENCE
            # broke — a peer died between completing this round and entering
            # the next. If the copy is consistent for (epoch, round), the
            # round happened: return it so survivors keep the progress (and
            # the checkpoint) it carries. The failure still surfaces at the
            # next round's entry fail-fast. A torn copy re-raises. Late
            # copiers are safe because after an abort no rank writes slots
            # again (entry fail-fast precedes the slot write) and `abort`
            # never clobbers a current-epoch payload.
            if not all(
                isinstance(item, tuple)
                and item[0] == self._epoch
                and item[1] == round_index
                for item in out_tagged
            ):
                raise
        out: List[str] = []
        for r, item in enumerate(out_tagged):
            aborted = parse_abort(item) if isinstance(item, str) else None
            if aborted is not None:
                self._raise_rank_failed(aborted[0], aborted[1], round_index)
            if (
                not isinstance(item, tuple)
                or item[0] != self._epoch
                or item[1] != round_index
            ):
                from .. import telemetry

                telemetry.registry().inc("rendezvous.timeouts")
                raise RendezvousTimeoutError(
                    f"rendezvous round {round_index}: rank {r} delivered a "
                    "payload from a different epoch/round (desync after a "
                    "failed attempt)",
                    round_index=round_index,
                    missing_ranks=[r],
                    timeout_s=timeout_s,
                )
            out.append(item[2])
        return out


class BarrierRendezvous(Rendezvous):
    """Adapter over a Spark `BarrierTaskContext`-shaped object — anything with
    ``allGather(str) -> list[str]`` plus a task-info surface. This is the
    control plane the reference uses directly (cuml_context.py:80-103,
    utils.py:205-207): running the framework inside a Spark barrier stage means
    constructing ``TpuContext(rank, nranks, BarrierRendezvous(ctx))`` in the
    task body, exactly where the reference builds its CumlContext."""

    def __init__(self, barrier_ctx, rank: Optional[int] = None, nranks: Optional[int] = None):
        self._ctx = barrier_ctx
        if rank is None:
            rank = int(barrier_ctx.partitionId())
        if nranks is None:
            infos = barrier_ctx.getTaskInfos()
            nranks = len(infos)
        self.rank = rank
        self.nranks = nranks

    def _allgather_impl(self, payload: str) -> List[str]:
        return list(self._ctx.allGather(payload))


class FileRendezvous(Rendezvous):
    """Cross-PROCESS rendezvous over a shared directory.

    The control plane for multi-process SPMD launches outside Spark (and for
    the subprocess test harness): each rank writes its payload to
    ``<dir>/round_<i>/rank_<r>`` and polls until all N files exist — the same
    allgather-of-strings contract the reference gets from
    `BarrierTaskContext.allGather` (reference cuml_context.py:80-103). Works on
    any shared filesystem; write-then-rename makes each file's appearance
    atomic.
    """

    can_reform = True

    def __init__(
        self,
        rank: int,
        nranks: int,
        root: str,
        timeout_s: Optional[float] = None,
        run_id: Optional[str] = None,
        heartbeat_interval_s: Optional[float] = None,
        live_ranks: Optional[List[int]] = None,
        anchor_root: Optional[str] = None,
    ):
        """`run_id` should be a fresh nonce minted by the LAUNCHER and passed to
        every rank — it namespaces this run's rounds so stale files from a
        previous run in the same root can never be read as current. Without it,
        the caller must guarantee `root` is a fresh directory per run.

        `anchor_root` (set by `reform`, never by launchers) pins the reform /
        rejoin coordination directory to the ORIGINAL run root across
        generations: reformed planes nest under ``<anchor>/reform_g<N>/plane``,
        so a respawned rank constructing over the original root and a
        twice-reformed survivor still agree on where membership windows open
        and where rejoin markers appear.

        `timeout_s` is the per-round deadline (None -> the framework's
        ``config["rendezvous_timeout_s"]``). `heartbeat_interval_s` (None ->
        ``config["heartbeat_interval_s"]``) paces the liveness file each rank
        touches from a daemon thread; survivors declare a pending rank dead —
        and raise RankFailedError — when its heartbeat is staler than
        1.5x the interval, so a SIGKILLed peer surfaces within 2x the
        interval instead of after the full round deadline. All ranks must be
        configured with the SAME interval."""
        self.rank = rank
        self.nranks = nranks
        self.root = os.path.join(root, run_id) if run_id else root
        self._anchor = anchor_root if anchor_root else self.root
        self.timeout_s = timeout_s
        self._round = 0
        self._epoch = 0
        if heartbeat_interval_s is None:
            from ..core import config

            heartbeat_interval_s = float(config.get("heartbeat_interval_s", 5.0))
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        # per-peer (last observed mtime, local monotonic when first observed):
        # staleness is measured as LACK OF MTIME PROGRESS on our own monotonic
        # clock, never writer-clock vs reader-clock — cross-host skew on a
        # shared FS must not kill healthy ranks
        self._hb_seen: dict = {}
        self._live_ranks = list(live_ranks) if live_ranks is not None else None
        os.makedirs(self.root, exist_ok=True)
        # stale-state hygiene: when the caller reuses a root WITHOUT a fresh
        # run_id, a previous crashed run's `abort_rank_<r>` file for OUR rank
        # would poison this run's peers into declaring us instantly dead —
        # each rank removes its own stale abort markers (every epoch prefix)
        # before any peer can scan them. run_id-namespaced roots never
        # collide, so this is a no-op there.
        if run_id is None:
            pat = re.compile(
                rf"^((e\d+_)?abort|rejoin_wait)_rank_{self.rank}$"
            )
            try:
                for name in os.listdir(self.root):
                    if pat.match(name):
                        with contextlib.suppress(OSError):
                            os.unlink(os.path.join(self.root, name))
            except OSError:  # pragma: no cover - racing cleanup is best-effort
                pass
            if anchor_root is None:
                self._clean_stale_reform_dirs()
        # heartbeat from CONSTRUCTION, not first allgather: a rank that dies
        # between the two leaves a STALE file (detectable within the
        # staleness window) instead of NO file (indistinguishable from a
        # peer still importing, so survivors would wait out the full round
        # deadline — found by the kill-at-round-0 chaos sweep)
        self._ensure_heartbeat()

    def _clean_stale_reform_dirs(self) -> None:
        """Root-reuse hygiene (no run_id, original-root construction only): a
        previous crashed run's ``reform_g*`` trees would poison this run's
        first recovery epoch — stale member votes close the window instantly
        with the wrong live set, and the stale plane's round files corrupt
        the confirmation allgather. Only trees with NO recent file activity
        are removed: a LIVE window (a peer already reforming, or survivors
        still heartbeating on a reformed plane while we respawn) keeps fresh
        vote/heartbeat mtimes and is left alone."""
        import shutil

        bound = max(
            60.0,
            2.0 * self._round_timeout_s(),
            4.0 * max(0.0, self.heartbeat_interval_s),
        )
        now = time.time()
        try:
            names = [
                n for n in os.listdir(self.root) if re.match(r"^reform_g\d+$", n)
            ]
        except OSError:  # pragma: no cover - root vanished
            return
        for name in names:
            tree = os.path.join(self.root, name)
            newest = 0.0
            for dirpath, _dirnames, filenames in os.walk(tree):
                for entry in [dirpath] + [os.path.join(dirpath, f) for f in filenames]:
                    with contextlib.suppress(OSError):
                        newest = max(newest, os.path.getmtime(entry))
            if now - newest > bound:  # wallclock-ok: compared against file mtimes, which are wall-clock — monotonic would be the wrong clock here
                shutil.rmtree(tree, ignore_errors=True)

    # -- file layout -------------------------------------------------------
    def _eprefix(self) -> str:
        """Epoch namespace for round/abort files ('' for the first attempt —
        the historical layout — so single-attempt runs keep their file names)."""
        return "" if self._epoch == 0 else f"e{self._epoch}_"

    def _abort_path(self, rank: int) -> str:
        return os.path.join(self.root, f"{self._eprefix()}abort_rank_{rank}")

    def _heartbeat_path(self, rank: int) -> str:
        return os.path.join(self.root, f"heartbeat_rank_{rank}")

    def _rejoin_wait_path(self, orig_rank: int) -> str:
        # keyed by ORIGINAL rank id (stable across reforms), epoch-less (the
        # marker describes an incarnation, not a round), and ANCHORED at the
        # original run root — a respawn writing over the original root and a
        # reformed survivor scanning from its g<N> plane must agree on it
        return os.path.join(self._anchor, f"rejoin_wait_rank_{orig_rank}")

    # -- heartbeat ---------------------------------------------------------
    def _touch_heartbeat(self) -> None:
        path = self._heartbeat_path(self.rank)
        try:
            with open(path, "a"):
                pass
            os.utime(path, None)
        except OSError:  # pragma: no cover - transient FS hiccup; next beat retries
            pass

    def _ensure_heartbeat(self) -> None:
        if self.heartbeat_interval_s <= 0:  # escape hatch: liveness via deadline only
            return
        if self._hb_thread is not None and self._hb_thread.is_alive():
            return
        self._touch_heartbeat()

        def beat() -> None:
            # Event.wait(interval) is the pacing AND the stop signal; a
            # SIGKILL stops the touches instantly — which is the point.
            while not self._hb_stop.wait(self.heartbeat_interval_s):
                self._touch_heartbeat()

        self._hb_stop.clear()
        self._hb_thread = threading.Thread(
            target=beat, name=f"srml-heartbeat-rank{self.rank}", daemon=True
        )
        self._hb_thread.start()

    def close(self) -> None:
        """Stop the heartbeat thread (daemonized, so leaking one is harmless —
        but long-lived launchers creating many rendezvous should close)."""
        self._hb_stop.set()

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self._hb_stop.set()
        except Exception:
            pass

    # -- abort channel -----------------------------------------------------
    def abort(self, reason: str) -> None:
        """Publish ``abort_rank_<rank>`` (write-then-rename, atomic appearance)
        carrying the ABORT sentinel; survivors' poll loops see it within one
        poll tick and raise RankFailedError."""
        from .. import diagnostics, telemetry

        tmp = os.path.join(self.root, f".abort_rank_{self.rank}.tmp")
        try:
            with open(tmp, "w") as f:
                f.write(format_abort(self.rank, reason))
            os.replace(tmp, self._abort_path(self.rank))
        except OSError:  # pragma: no cover - abort is best-effort by design
            return
        telemetry.registry().inc("rendezvous.aborts_published")
        diagnostics.record_event("abort_published", reason=str(reason)[:200])
        diagnostics.flight_recorder().dump(reason="abort published")

    def begin_epoch(self, epoch: int) -> None:
        self._epoch = int(epoch)
        self._round = 0
        from .. import diagnostics

        diagnostics.record_event("epoch_begin", epoch=int(epoch))

    # -- membership reform (elastic recovery) -----------------------------
    def _reform_dir(self, generation: int) -> str:
        # anchored: generation N+1's window must be discoverable both by
        # survivors rooted at the g<N> plane and by a respawn constructing
        # over the ORIGINAL root
        return os.path.join(self._anchor, f"reform_g{int(generation)}")

    def latest_generation(self) -> Optional[int]:
        """Highest reform generation already opened under the anchor root
        (how a respawned rank discovers which epoch boundary to rejoin at)."""
        best = None
        try:
            for name in os.listdir(self._anchor):
                m = re.match(r"^reform_g(\d+)$", name)
                if m:
                    g = int(m.group(1))
                    best = g if best is None else max(best, g)
        except OSError:  # pragma: no cover - root vanished
            return None
        return best

    def rejoin(self, generation: Optional[int] = None) -> "FileRendezvous":
        """Respawned-rank entry point: vote in the open reform round (found
        via `latest_generation` when not given) and join the reformed group
        at the epoch boundary. With no generation given, POLLS for a reform
        window to open (deadline-bounded) — a respawned process typically
        launches while survivors are still detecting the death, before any
        window exists. The survivors' window must still be open when the vote
        lands (``config["recovery_rejoin_grace_s"]`` keeps it open for
        prompt respawns).

        Entry publishes a ``rejoin_wait_rank_<orig>`` marker FIRST: this
        incarnation's heartbeat resumes touching the dead rank's liveness
        file from construction, which would otherwise make the corpse look
        alive to survivors blocked in a round — they'd wait out the full
        round deadline instead of detecting the death within the heartbeat
        budget (and this rejoiner's window poll can expire before any reform
        opens). The marker is positive evidence the ORIGINAL incarnation
        died, so survivors raise RankFailedError within one failure-scan
        tick and the reform window opens while we are still polling for it.
        The marker is removed on admission."""
        me = self.orig_rank
        tmp = os.path.join(self.root, f".rejoin_wait_rank_{me}.tmp")
        try:
            with open(tmp, "w") as f:
                f.write(json.dumps({"rank": me, "t": time.time()}))  # sink-ok: control-plane marker payload, not a telemetry record
            os.replace(tmp, self._rejoin_wait_path(me))
        except OSError:  # pragma: no cover - best-effort; survivors fall back to the round deadline
            pass
        if generation is None:
            deadline = time.monotonic() + self._round_timeout_s()
            while True:  # blocking-ok: deadline-bounded window poll
                generation = self.latest_generation()
                if generation is not None:
                    break
                if time.monotonic() > deadline:
                    raise RendezvousTimeoutError(
                        "rejoin: no reform round opened under this root "
                        "within the deadline",
                        timeout_s=self._round_timeout_s(),
                    )
                time.sleep(0.02)  # sleep-ok: poll tick inside the deadline-bounded rejoin wait
        reformed = self.reform(dead_ranks=(), generation=generation)
        with contextlib.suppress(OSError):
            os.unlink(self._rejoin_wait_path(me))
        return reformed

    def reform(self, dead_ranks=(), generation: int = 1) -> "FileRendezvous":
        """File-substrate membership reform.

        Each participant votes by writing ``member_rank_<orig>`` under
        ``reform_g<generation>`` (write-then-rename), then waits until every
        currently-expected rank has either voted or is evidently dead (its
        abort file exists, or its heartbeat/vote never materializes within
        the staleness window). Votes from OUTSIDE the expected set — a
        respawned rank rejoining — are admitted. The window stays open at
        least ``config["recovery_rejoin_grace_s"]`` so a prompt respawn is
        admitted deterministically. The agreed live set is then CONFIRMED
        with one allgather round on the reformed plane: any membership
        mismatch (a straggler vote landing after one side closed) surfaces
        as the transient `RendezvousTimeoutError`, never a silently split
        group."""
        from .. import diagnostics, telemetry
        from ..core import config

        generation = int(generation)
        member_dir = self._reform_dir(generation)
        os.makedirs(member_dir, exist_ok=True)
        me = self.orig_rank
        tmp = os.path.join(member_dir, f".member_rank_{me}.tmp")
        with open(tmp, "w") as f:
            f.write(json.dumps({"rank": me, "t": time.time()}))  # sink-ok: control-plane vote payload, not a telemetry record
        os.replace(tmp, os.path.join(member_dir, f"member_rank_{me}"))

        dead = {int(r) for r in dead_ranks}
        expected = set(self.live_ranks)
        live_map = self.live_ranks  # current index <- position of orig id
        stale_after = (
            _HEARTBEAT_MISS_FACTOR * self.heartbeat_interval_s
            if self.heartbeat_interval_s > 0
            else 2.0
        )
        grace = float(config.get("recovery_rejoin_grace_s", 0.0))
        timeout_s = self._round_timeout_s()
        start = time.monotonic()
        deadline = start + timeout_s
        member_pat = re.compile(r"^member_rank_(\d+)$")
        while True:  # blocking-ok: deadline- and staleness-bounded vote scan
            filed = set()
            for name in os.listdir(member_dir):
                m = member_pat.match(name)
                if m:
                    filed.add(int(m.group(1)))
            now_m = time.monotonic()
            pending = expected - filed - dead
            for r in list(pending):
                cur = live_map.index(r)
                if os.path.exists(self._abort_path(cur)):
                    dead.add(r)
                    pending.discard(r)
                    continue
                # no vote yet: alive only if its heartbeat keeps progressing
                try:
                    mtime = os.path.getmtime(self._heartbeat_path(cur))
                except OSError:
                    mtime = None
                seen = self._hb_seen.get(("reform", r))
                if mtime is not None and (seen is None or mtime != seen[0]):
                    self._hb_seen[("reform", r)] = (mtime, now_m)
                    continue
                base_t = seen[1] if seen is not None else start
                if now_m - base_t > stale_after:
                    dead.add(r)
                    pending.discard(r)
            if not pending and (
                now_m - start >= grace
                # every ORIGINALLY-expected member (incl. a respawned
                # incarnation of a dead rank) has voted: no further vote can
                # arrive, so the grace window may close early — a prompt
                # rejoin doesn't cost survivors the full grace wait
                or filed >= expected
            ):
                break
            if now_m > deadline:
                telemetry.registry().inc("rendezvous.timeouts")
                raise RendezvousTimeoutError(
                    f"reform generation {generation}: ranks {sorted(pending)} "
                    f"neither voted nor died within {timeout_s}s",
                    missing_ranks=sorted(pending),
                    timeout_s=timeout_s,
                )
            time.sleep(0.01)  # sleep-ok: poll tick inside the deadline-bounded reform scan
        # a VOTE proves a live process — the dead set only governs who the
        # window stops waiting for. A respawned incarnation of a killed rank
        # that votes inside the window is admitted even though its original
        # id was seeded dead (that is the whole rejoin path).
        live = sorted(filed)
        if me not in live or not live:
            raise RankFailedError(
                me, "this rank was excluded by the reform round", round_index=None
            )
        new = FileRendezvous(
            live.index(me),
            len(live),
            os.path.join(member_dir, "plane"),
            timeout_s=self.timeout_s,
            heartbeat_interval_s=self.heartbeat_interval_s,
            live_ranks=live,
            anchor_root=self._anchor,
        )
        new.reform_generation = generation
        # confirmation round: every member states the set it computed; a
        # mismatch means a vote landed after somebody closed the window
        confirmed = new.allgather("REFORM:" + json.dumps(live))
        if any(p != confirmed[0] for p in confirmed):
            telemetry.registry().inc("rendezvous.timeouts")
            raise RendezvousTimeoutError(
                f"reform generation {generation}: members disagree on the "
                "live set (vote landed after the window closed)",
                timeout_s=timeout_s,
            )
        telemetry.registry().inc("rendezvous.reforms")
        diagnostics.record_event(
            "recovery_reform", generation=generation, survivors=live,
            dead=sorted(dead),
        )
        return new

    def _check_failures(self, pending, round_index: int) -> None:
        """Raise RankFailedError when any rank published an abort for this
        epoch, a PENDING peer's respawned incarnation announced it is
        waiting to rejoin (the original is dead even though the respawn's
        heartbeat keeps the liveness file fresh), or a PENDING peer's
        heartbeat went stale (killed process — it cannot publish
        anything)."""
        for r in range(self.nranks):
            if r == self.rank:
                continue
            path = self._abort_path(r)
            if os.path.exists(path):
                try:
                    with open(path) as f:
                        parsed = parse_abort(f.read())
                except OSError:
                    parsed = None
                rank, reason = parsed if parsed is not None else (r, "abort file unreadable")
                self._raise_rank_failed(rank, reason, round_index)
        live = self.live_ranks
        for r in pending:
            if r == self.rank:
                continue
            # a rejoin marker is POSITIVE death evidence for the original
            # incarnation — and it must outrank heartbeat progress, because
            # the respawn resumes touching the same liveness file from
            # construction (a corpse that looks alive would otherwise pin
            # survivors in this round until the full deadline)
            if os.path.exists(self._rejoin_wait_path(live[r])):
                # raise the CURRENT index (like the abort/heartbeat paths —
                # recoverable_stage maps failed_rank through live_ranks once;
                # raising the original id here would double-map it after a
                # prior reform and blame an innocent survivor)
                self._raise_rank_failed(
                    r,
                    f"process died (original rank {live[r]}); a respawned "
                    "incarnation is waiting to rejoin at the next reform round",
                    round_index,
                )
        if self.heartbeat_interval_s <= 0:
            return
        stale_after = _HEARTBEAT_MISS_FACTOR * self.heartbeat_interval_s
        now_m = time.monotonic()
        for r in pending:
            if r == self.rank:
                continue
            try:
                mtime = os.path.getmtime(self._heartbeat_path(r))
            except OSError:
                continue  # not started yet — only the round deadline applies
            seen = self._hb_seen.get(r)
            if seen is None or mtime != seen[0]:
                self._hb_seen[r] = (mtime, now_m)  # progress observed — alive
                continue
            stale_for = now_m - seen[1]
            if stale_for > stale_after:
                self._raise_rank_failed(
                    r,
                    f"heartbeat stale for {stale_for:.2f}s "
                    f"(interval {self.heartbeat_interval_s}s) — process presumed dead",
                    round_index,
                )

    def _allgather_impl(self, payload: str) -> List[str]:
        self._ensure_heartbeat()
        round_index = self._round
        round_dir = os.path.join(self.root, f"{self._eprefix()}round_{round_index}")
        self._round += 1
        os.makedirs(round_dir, exist_ok=True)
        tmp = os.path.join(round_dir, f".rank_{self.rank}.tmp")
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, os.path.join(round_dir, f"rank_{self.rank}"))
        timeout_s = self._round_timeout_s()
        deadline = time.monotonic() + timeout_s
        out: List[Optional[str]] = [None] * self.nranks
        pending = set(range(self.nranks))
        next_failure_scan = 0.0  # first iteration scans immediately
        while pending:  # blocking-ok: deadline- and heartbeat-bounded poll
            for r in list(pending):
                path = os.path.join(round_dir, f"rank_{r}")
                if os.path.exists(path):
                    with open(path) as f:
                        out[r] = f.read()
                    pending.discard(r)
            if pending:
                now_m = time.monotonic()
                # round files poll at 5ms, but the failure scan (abort files +
                # heartbeat mtimes: O(nranks) stats against a possibly-shared
                # FS) is throttled — ~50ms detection granularity meets every
                # promised budget without a stat storm
                if now_m >= next_failure_scan:
                    self._check_failures(pending, round_index)
                    next_failure_scan = now_m + _FAILURE_SCAN_INTERVAL_S
                if now_m > deadline:
                    self._raise_timeout(round_index, sorted(pending), timeout_s)
                time.sleep(0.005)  # sleep-ok: poll tick inside the deadline-bounded round wait
        return out  # type: ignore[return-value]


def _free_port() -> int:
    with socket.socket() as s:  # exporter-ok: jax.distributed coordinator port probe, not a metrics endpoint
        s.bind(("", 0))
        return s.getsockname()[1]


def _distributed_is_initialized() -> bool:
    """`jax.distributed.is_initialized()` where available (newer jax); on
    0.4.x fall back to the distributed global state's client handle. Both
    probe WITHOUT touching the XLA backend (unlike jax.process_count())."""
    import jax

    checker = getattr(jax.distributed, "is_initialized", None)
    if checker is not None:
        return bool(checker())
    try:
        from jax._src import distributed as _dist

        return getattr(_dist.global_state, "client", None) is not None
    except Exception:  # pragma: no cover - conservative default
        return False


# The context active for the current fit call, set by TpuContext.__enter__.
# Estimators pick this up so `with TpuContext(...): est.fit(local_df)` routes
# the fit through the caller's process group — the analog of the reference's
# train-UDF body running inside its CumlContext (reference core.py:768-781).
_ACTIVE_CONTEXT: Optional["TpuContext"] = None


class TpuContext:
    """Context manager that stands up the per-job process group and mesh.

    Modes:
      * ``nranks == 1`` or single-controller (one process drives all local
        devices): no distributed init; mesh spans local devices.
      * SPMD multi-process: rank0 advertises ``host:port`` through the
        rendezvous, every rank calls ``jax.distributed.initialize``; the mesh
        then spans the global device list. ICI carries collectives within a pod
        slice, DCN across slices — no in-tree data plane is needed (the UCX
        layer of the reference has no TPU analog).
    """

    def __init__(
        self,
        rank: int,
        nranks: int,
        rendezvous: Optional[Rendezvous] = None,
        *,
        require_distributed: bool = False,
        num_devices: Optional[int] = None,
    ):
        self.rank = rank
        self.nranks = nranks
        self.rendezvous = rendezvous
        self.require_distributed = require_distributed
        self.num_devices = num_devices
        self.mesh = None
        self._initialized_distributed = False
        self._prev_active: Optional["TpuContext"] = None

    @classmethod
    def current(cls) -> Optional["TpuContext"]:
        """The context entered by the caller, if any (estimators consult this)."""
        return _ACTIVE_CONTEXT

    def adopt_reform(self, new_rendezvous: "Rendezvous") -> None:
        """Adopt a reformed (survivor) rendezvous: renumbered rank/nranks,
        and the mesh rebuilt over the survivors' devices (the dead rank's
        chips leave the mesh; its row shards are re-placed from
        host-retained ingest chunks when the fit re-enters). Called by
        `core.recoverable_stage` at each recovery epoch."""
        old_live = set(self.live_ranks_hint())
        self.rendezvous = new_rendezvous
        self.rank = new_rendezvous.rank
        self.nranks = new_rendezvous.nranks
        self.recovery_generation = int(getattr(new_rendezvous, "reform_generation", 0))
        dead_procs = old_live - set(
            getattr(new_rendezvous, "live_ranks", range(new_rendezvous.nranks))
        )
        if self.mesh is not None and dead_procs:
            import jax

            from .mesh import survivor_mesh

            if jax.process_count() > 1:
                try:
                    self.mesh = survivor_mesh(self.mesh, dead_procs)
                except Exception as e:  # pragma: no cover - backend-specific
                    from ..utils import get_logger

                    get_logger("TpuContext").warning(
                        "could not rebuild the mesh over survivors (%s: %s); "
                        "keeping the previous mesh", type(e).__name__, e,
                    )

    def live_ranks_hint(self) -> List[int]:
        """Original rank ids of the current membership (identity when the
        rendezvous tracks none)."""
        if self.rendezvous is not None:
            return list(getattr(self.rendezvous, "live_ranks", range(self.nranks)))
        return list(range(self.nranks))

    @property
    def is_spmd(self) -> bool:
        """True when each rank holds only its LOCAL row block (multi-process
        SPMD), so estimators must rendezvous for global layout/host stats."""
        return self.nranks > 1

    def __enter__(self) -> "TpuContext":
        global _ACTIVE_CONTEXT
        import jax

        if self.nranks > 1:
            # nranks > 1 always means multi-process SPMD: the process group
            # must be live and a control-plane rendezvous present, or ranks
            # would silently fit their local block as if it were global
            if self.rendezvous is None:
                raise RuntimeError(
                    "TpuContext with nranks > 1 needs a rendezvous (control-plane "
                    "allgather for partition layout and host-side statistics)"
                )
            # probe distributed state WITHOUT jax.process_count(): that call
            # initializes the XLA backend, after which distributed init is
            # rejected
            if not _distributed_is_initialized():
                if self.rank == 0:
                    coordinator = json.dumps({"addr": f"{socket.gethostname()}:{_free_port()}"})
                else:
                    coordinator = json.dumps({})
                gathered = self.rendezvous.allgather(coordinator)
                addr = json.loads(gathered[0])["addr"]
                jax.distributed.initialize(
                    coordinator_address=addr, num_processes=self.nranks, process_id=self.rank
                )
                self._initialized_distributed = True
            if jax.process_count() != self.nranks:
                raise RuntimeError(
                    f"jax.distributed is initialized with {jax.process_count()} "
                    f"processes but TpuContext was built for nranks={self.nranks}"
                )

        from .mesh import get_mesh

        self.mesh = get_mesh(self.num_devices)
        self._prev_active = _ACTIVE_CONTEXT
        _ACTIVE_CONTEXT = self
        return self

    def __exit__(self, exc_type, exc_val, exc_tb) -> bool:
        global _ACTIVE_CONTEXT
        import jax

        _ACTIVE_CONTEXT = self._prev_active
        if (
            self.rendezvous is not None
            and exc_type is not None
            and not (isinstance(exc_val, RankFailedError) or issubclass(exc_type, RankFailedError))
        ):
            # propagate the failure FIRST (before any local teardown) so peers
            # blocked in a rendezvous round unwind within one failure scan —
            # the abort-on-exception side of the reference's destroy-on-
            # success / abort-on-exception teardown (cuml_context.py:150-167).
            # A RankFailedError is NOT re-published: we are relaying a peer's
            # failure, and a cascade of abort files would let later scanners
            # blame a healthy survivor instead of the root-cause rank. Abort
            # is best-effort and must never mask the original exception.
            try:
                self.rendezvous.abort(f"{exc_type.__name__}: {exc_val}")
            except Exception:
                pass
        if self._initialized_distributed:
            # destroy on success, abort-equivalent on exception
            # (reference cuml_context.py:150-167)
            try:
                jax.distributed.shutdown()
            except Exception:
                pass
        if self.rendezvous is not None and exc_type is None:
            # success-path sync is BOUNDED: a peer that already exited (or
            # died without publishing) must not hang our teardown forever. A
            # timeout here is a warning, not an error — our own work
            # succeeded; a published peer failure still propagates.
            from ..core import config
            from ..utils import get_logger

            teardown_s = min(
                float(config.get("teardown_timeout_s", 15.0)),
                self.rendezvous._round_timeout_s(),
            )
            try:
                self.rendezvous.barrier(timeout_s=teardown_s)
            except RendezvousTimeoutError:
                get_logger("TpuContext").warning(
                    "teardown barrier timed out after %.1fs (a peer already "
                    "exited?); continuing — local results are complete",
                    teardown_s,
                )
            except RankFailedError as e:
                # a peer died between finishing its work and the teardown
                # sync: OUR fit succeeded, so this is a warning, not an error
                # — failing here would discard completed local results
                get_logger("TpuContext").warning(
                    "peer failure during teardown barrier (%s); continuing — "
                    "local results are complete", e,
                )
        return False
