#
# Distributed process-group context — the TPU-native replacement for the
# reference's `CumlContext` (reference common/cuml_context.py:36-167), which
# builds a NCCL clique (rank0 mints a uid, BarrierTaskContext.allGather
# broadcasts it, each rank nccl.init) plus an optional UCX endpoint mesh.
#
# On TPU there is no uid/endpoint plumbing: each worker process calls
# `jax.distributed.initialize(coordinator, num_processes, process_id)` and XLA
# compiles collectives onto ICI/DCN. What remains of the reference design is the
# *rendezvous pattern*: rank0 picks the coordinator endpoint and an
# allgather-of-strings control plane distributes it — exactly where the
# reference broadcasts the NCCL uid. Teardown mirrors destroy-on-success /
# abort-on-exception (cuml_context.py:150-167).
#
from __future__ import annotations

import json
import socket
import threading
from typing import List, Optional

__all__ = ["Rendezvous", "LocalRendezvous", "TpuContext"]


class Rendezvous:
    """Control-plane interface: allgather small strings + barrier.

    Implementations: `LocalRendezvous` (in-process threads, for tests and
    single-controller mode), and — when running under Spark barrier stages — a
    thin wrapper over `BarrierTaskContext` (see spark/integration module) whose
    `allGather` this API is shaped after.
    """

    rank: int
    nranks: int

    def allgather(self, payload: str) -> List[str]:
        raise NotImplementedError

    def barrier(self) -> None:
        self.allgather("")


class LocalRendezvous(Rendezvous):
    """Thread-barrier rendezvous for N ranks inside one process (test harness).

    The analog of running the reference's barrier stage in Spark local mode
    (tests/conftest.py:44-70 there): real collective code paths, one machine.
    """

    class _Shared:
        def __init__(self, nranks: int):
            self.barrier = threading.Barrier(nranks)
            self.slots: List[Optional[str]] = [None] * nranks
            self.lock = threading.Lock()

    def __init__(self, rank: int, shared: "_Shared"):
        self.rank = rank
        self.nranks = shared.barrier.parties
        self._shared = shared

    @classmethod
    def create(cls, nranks: int) -> List["LocalRendezvous"]:
        shared = cls._Shared(nranks)
        return [cls(r, shared) for r in range(nranks)]

    def allgather(self, payload: str) -> List[str]:
        self._shared.slots[self.rank] = payload
        self._shared.barrier.wait()
        out = list(self._shared.slots)  # type: ignore[arg-type]
        self._shared.barrier.wait()  # don't let a fast rank overwrite slots early
        return out  # type: ignore[return-value]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


class TpuContext:
    """Context manager that stands up the per-job process group and mesh.

    Modes:
      * ``nranks == 1`` or single-controller (one process drives all local
        devices): no distributed init; mesh spans local devices.
      * SPMD multi-process: rank0 advertises ``host:port`` through the
        rendezvous, every rank calls ``jax.distributed.initialize``; the mesh
        then spans the global device list. ICI carries collectives within a pod
        slice, DCN across slices — no in-tree data plane is needed (the UCX
        layer of the reference has no TPU analog).
    """

    def __init__(
        self,
        rank: int,
        nranks: int,
        rendezvous: Optional[Rendezvous] = None,
        *,
        require_distributed: bool = False,
        num_devices: Optional[int] = None,
    ):
        self.rank = rank
        self.nranks = nranks
        self.rendezvous = rendezvous
        self.require_distributed = require_distributed
        self.num_devices = num_devices
        self.mesh = None
        self._initialized_distributed = False

    def __enter__(self) -> "TpuContext":
        import jax

        if self.require_distributed and self.nranks > 1 and jax.process_count() == 1:
            assert self.rendezvous is not None, "multi-process TpuContext needs a rendezvous"
            if self.rank == 0:
                coordinator = json.dumps({"addr": f"{socket.gethostname()}:{_free_port()}"})
            else:
                coordinator = json.dumps({})
            gathered = self.rendezvous.allgather(coordinator)
            addr = json.loads(gathered[0])["addr"]
            jax.distributed.initialize(
                coordinator_address=addr, num_processes=self.nranks, process_id=self.rank
            )
            self._initialized_distributed = True

        from .mesh import get_mesh

        self.mesh = get_mesh(self.num_devices)
        return self

    def __exit__(self, exc_type, exc_val, exc_tb) -> bool:
        import jax

        if self._initialized_distributed:
            # destroy on success, abort-equivalent on exception
            # (reference cuml_context.py:150-167)
            try:
                jax.distributed.shutdown()
            except Exception:
                pass
        if self.rendezvous is not None and exc_type is None:
            self.rendezvous.barrier()
        return False
