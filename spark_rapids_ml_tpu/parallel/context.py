#
# Distributed process-group context — the TPU-native replacement for the
# reference's `CumlContext` (reference common/cuml_context.py:36-167), which
# builds a NCCL clique (rank0 mints a uid, BarrierTaskContext.allGather
# broadcasts it, each rank nccl.init) plus an optional UCX endpoint mesh.
#
# On TPU there is no uid/endpoint plumbing: each worker process calls
# `jax.distributed.initialize(coordinator, num_processes, process_id)` and XLA
# compiles collectives onto ICI/DCN. What remains of the reference design is the
# *rendezvous pattern*: rank0 picks the coordinator endpoint and an
# allgather-of-strings control plane distributes it — exactly where the
# reference broadcasts the NCCL uid. Teardown mirrors destroy-on-success /
# abort-on-exception (cuml_context.py:150-167).
#
from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import List, Optional

__all__ = [
    "Rendezvous",
    "LocalRendezvous",
    "FileRendezvous",
    "TpuContext",
    "allgather_ndarray",
]


def allgather_ndarray(rendezvous: "Rendezvous", arr, chunk_bytes: Optional[int] = None) -> List:
    """Allgather a host numpy array through the string control plane (base64 of
    the .npy encoding); returns the per-rank arrays in rank order. The analog of
    the reference's base64-over-BarrierTaskContext.allGather payloads
    (reference tree.py:343, knn.py:689-700).

    Large arrays are split into row chunks of at most `chunk_bytes` (default:
    the framework's ``config["broadcast_chunk_bytes"]`` — the reference's 8 GB
    broadcast-chunking knob, clustering.py:1013-1091) so no single control-plane
    round carries an unbounded payload."""
    import base64
    import io

    import numpy as np

    if chunk_bytes is None:
        from ..core import config

        chunk_bytes = int(config.get("broadcast_chunk_bytes", 8 << 30))

    arr = np.ascontiguousarray(arr)
    if arr.ndim == 0:  # scalars can't be row-chunked; one round carries them
        buf = io.BytesIO()
        np.save(buf, arr, allow_pickle=False)
        payloads = rendezvous.allgather(base64.b64encode(buf.getvalue()).decode("ascii"))
        return [
            np.load(io.BytesIO(base64.b64decode(p)), allow_pickle=False)
            for p in payloads
        ]
    row_bytes = max(1, arr[:1].nbytes)
    rows_per_chunk = max(1, chunk_bytes // row_bytes)
    n = arr.shape[0]
    n_chunks = max(1, -(-n // rows_per_chunk))
    # every rank must agree on the ROUND COUNT, not just its own chunking
    n_chunks = max(
        int(p) for p in rendezvous.allgather(str(n_chunks))
    )
    rows_per_chunk = max(1, -(-n // n_chunks))

    def ser(a):
        buf = io.BytesIO()
        np.save(buf, a, allow_pickle=False)
        return base64.b64encode(buf.getvalue()).decode("ascii")

    def de(p):
        return np.load(io.BytesIO(base64.b64decode(p)), allow_pickle=False)

    gathered_chunks: List[List] = []
    for c in range(n_chunks):
        part = arr[c * rows_per_chunk : (c + 1) * rows_per_chunk]
        gathered_chunks.append([de(p) for p in rendezvous.allgather(ser(part))])
    out = []
    for r in range(rendezvous.nranks):
        parts = [gathered_chunks[c][r] for c in range(n_chunks)]
        out.append(np.concatenate(parts, axis=0) if len(parts) > 1 else parts[0])
    return out


def allgather_concat(rendezvous: "Rendezvous", arr):
    """Gather every rank's row block and concatenate in rank order; returns
    ``(global_array, this_rank_row_offset)`` — the shared idiom behind the
    replicated-data strategies (DBSCAN full-set gather, ANN/kNN query
    replication, UMAP fit-sample union)."""
    import numpy as np

    blocks = allgather_ndarray(rendezvous, arr)
    offset = sum(len(b) for b in blocks[: rendezvous.rank])
    return np.concatenate(blocks, axis=0), offset


class Rendezvous:
    """Control-plane interface: allgather small strings + barrier.

    Implementations: `LocalRendezvous` (in-process threads, for tests and
    single-controller mode), and — when running under Spark barrier stages — a
    thin wrapper over `BarrierTaskContext` (see spark/integration module) whose
    `allGather` this API is shaped after.

    In-tree implementations provide `_allgather_impl`; the base `allgather`
    wraps it with telemetry (round-trip counter, payload bytes, latency
    histogram — rank-tagged, no collectives of its own). Out-of-tree
    subclasses overriding `allgather` directly keep working, minus telemetry.
    """

    rank: int
    nranks: int

    def allgather(self, payload: str) -> List[str]:
        from .. import telemetry

        if not telemetry.enabled():
            return self._allgather_impl(payload)
        with telemetry.span("rendezvous.allgather", nranks=self.nranks):
            out = self._allgather_impl(payload)
        reg = telemetry.registry()
        reg.inc("rendezvous.rounds")
        reg.inc("rendezvous.payload_bytes", len(payload))
        return out

    def _allgather_impl(self, payload: str) -> List[str]:
        raise NotImplementedError

    def barrier(self) -> None:
        self.allgather("")


class LocalRendezvous(Rendezvous):
    """Thread-barrier rendezvous for N ranks inside one process (test harness).

    The analog of running the reference's barrier stage in Spark local mode
    (tests/conftest.py:44-70 there): real collective code paths, one machine.
    """

    class _Shared:
        def __init__(self, nranks: int):
            self.barrier = threading.Barrier(nranks)
            self.slots: List[Optional[str]] = [None] * nranks
            self.lock = threading.Lock()

    def __init__(self, rank: int, shared: "_Shared"):
        self.rank = rank
        self.nranks = shared.barrier.parties
        self._shared = shared

    @classmethod
    def create(cls, nranks: int) -> List["LocalRendezvous"]:
        shared = cls._Shared(nranks)
        return [cls(r, shared) for r in range(nranks)]

    def _allgather_impl(self, payload: str) -> List[str]:
        self._shared.slots[self.rank] = payload
        self._shared.barrier.wait()
        out = list(self._shared.slots)  # type: ignore[arg-type]
        self._shared.barrier.wait()  # don't let a fast rank overwrite slots early
        return out  # type: ignore[return-value]


class BarrierRendezvous(Rendezvous):
    """Adapter over a Spark `BarrierTaskContext`-shaped object — anything with
    ``allGather(str) -> list[str]`` plus a task-info surface. This is the
    control plane the reference uses directly (cuml_context.py:80-103,
    utils.py:205-207): running the framework inside a Spark barrier stage means
    constructing ``TpuContext(rank, nranks, BarrierRendezvous(ctx))`` in the
    task body, exactly where the reference builds its CumlContext."""

    def __init__(self, barrier_ctx, rank: Optional[int] = None, nranks: Optional[int] = None):
        self._ctx = barrier_ctx
        if rank is None:
            rank = int(barrier_ctx.partitionId())
        if nranks is None:
            infos = barrier_ctx.getTaskInfos()
            nranks = len(infos)
        self.rank = rank
        self.nranks = nranks

    def _allgather_impl(self, payload: str) -> List[str]:
        return list(self._ctx.allGather(payload))


class FileRendezvous(Rendezvous):
    """Cross-PROCESS rendezvous over a shared directory.

    The control plane for multi-process SPMD launches outside Spark (and for
    the subprocess test harness): each rank writes its payload to
    ``<dir>/round_<i>/rank_<r>`` and polls until all N files exist — the same
    allgather-of-strings contract the reference gets from
    `BarrierTaskContext.allGather` (reference cuml_context.py:80-103). Works on
    any shared filesystem; write-then-rename makes each file's appearance
    atomic.
    """

    def __init__(
        self,
        rank: int,
        nranks: int,
        root: str,
        timeout_s: float = 300.0,
        run_id: Optional[str] = None,
    ):
        """`run_id` should be a fresh nonce minted by the LAUNCHER and passed to
        every rank — it namespaces this run's rounds so stale files from a
        previous run in the same root can never be read as current. Without it,
        the caller must guarantee `root` is a fresh directory per run."""
        self.rank = rank
        self.nranks = nranks
        self.root = os.path.join(root, run_id) if run_id else root
        self.timeout_s = timeout_s
        self._round = 0
        os.makedirs(self.root, exist_ok=True)

    def _allgather_impl(self, payload: str) -> List[str]:
        round_dir = os.path.join(self.root, f"round_{self._round}")
        self._round += 1
        os.makedirs(round_dir, exist_ok=True)
        tmp = os.path.join(round_dir, f".rank_{self.rank}.tmp")
        with open(tmp, "w") as f:
            f.write(payload)
        os.replace(tmp, os.path.join(round_dir, f"rank_{self.rank}"))
        deadline = time.monotonic() + self.timeout_s
        out: List[Optional[str]] = [None] * self.nranks
        pending = set(range(self.nranks))
        while pending:
            for r in list(pending):
                path = os.path.join(round_dir, f"rank_{r}")
                if os.path.exists(path):
                    with open(path) as f:
                        out[r] = f.read()
                    pending.discard(r)
            if pending:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"rendezvous round {self._round - 1}: ranks {sorted(pending)} "
                        f"missing after {self.timeout_s}s"
                    )
                time.sleep(0.01)
        return out  # type: ignore[return-value]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _distributed_is_initialized() -> bool:
    """`jax.distributed.is_initialized()` where available (newer jax); on
    0.4.x fall back to the distributed global state's client handle. Both
    probe WITHOUT touching the XLA backend (unlike jax.process_count())."""
    import jax

    checker = getattr(jax.distributed, "is_initialized", None)
    if checker is not None:
        return bool(checker())
    try:
        from jax._src import distributed as _dist

        return getattr(_dist.global_state, "client", None) is not None
    except Exception:  # pragma: no cover - conservative default
        return False


# The context active for the current fit call, set by TpuContext.__enter__.
# Estimators pick this up so `with TpuContext(...): est.fit(local_df)` routes
# the fit through the caller's process group — the analog of the reference's
# train-UDF body running inside its CumlContext (reference core.py:768-781).
_ACTIVE_CONTEXT: Optional["TpuContext"] = None


class TpuContext:
    """Context manager that stands up the per-job process group and mesh.

    Modes:
      * ``nranks == 1`` or single-controller (one process drives all local
        devices): no distributed init; mesh spans local devices.
      * SPMD multi-process: rank0 advertises ``host:port`` through the
        rendezvous, every rank calls ``jax.distributed.initialize``; the mesh
        then spans the global device list. ICI carries collectives within a pod
        slice, DCN across slices — no in-tree data plane is needed (the UCX
        layer of the reference has no TPU analog).
    """

    def __init__(
        self,
        rank: int,
        nranks: int,
        rendezvous: Optional[Rendezvous] = None,
        *,
        require_distributed: bool = False,
        num_devices: Optional[int] = None,
    ):
        self.rank = rank
        self.nranks = nranks
        self.rendezvous = rendezvous
        self.require_distributed = require_distributed
        self.num_devices = num_devices
        self.mesh = None
        self._initialized_distributed = False
        self._prev_active: Optional["TpuContext"] = None

    @classmethod
    def current(cls) -> Optional["TpuContext"]:
        """The context entered by the caller, if any (estimators consult this)."""
        return _ACTIVE_CONTEXT

    @property
    def is_spmd(self) -> bool:
        """True when each rank holds only its LOCAL row block (multi-process
        SPMD), so estimators must rendezvous for global layout/host stats."""
        return self.nranks > 1

    def __enter__(self) -> "TpuContext":
        global _ACTIVE_CONTEXT
        import jax

        if self.nranks > 1:
            # nranks > 1 always means multi-process SPMD: the process group
            # must be live and a control-plane rendezvous present, or ranks
            # would silently fit their local block as if it were global
            if self.rendezvous is None:
                raise RuntimeError(
                    "TpuContext with nranks > 1 needs a rendezvous (control-plane "
                    "allgather for partition layout and host-side statistics)"
                )
            # probe distributed state WITHOUT jax.process_count(): that call
            # initializes the XLA backend, after which distributed init is
            # rejected
            if not _distributed_is_initialized():
                if self.rank == 0:
                    coordinator = json.dumps({"addr": f"{socket.gethostname()}:{_free_port()}"})
                else:
                    coordinator = json.dumps({})
                gathered = self.rendezvous.allgather(coordinator)
                addr = json.loads(gathered[0])["addr"]
                jax.distributed.initialize(
                    coordinator_address=addr, num_processes=self.nranks, process_id=self.rank
                )
                self._initialized_distributed = True
            if jax.process_count() != self.nranks:
                raise RuntimeError(
                    f"jax.distributed is initialized with {jax.process_count()} "
                    f"processes but TpuContext was built for nranks={self.nranks}"
                )

        from .mesh import get_mesh

        self.mesh = get_mesh(self.num_devices)
        self._prev_active = _ACTIVE_CONTEXT
        _ACTIVE_CONTEXT = self
        return self

    def __exit__(self, exc_type, exc_val, exc_tb) -> bool:
        global _ACTIVE_CONTEXT
        import jax

        _ACTIVE_CONTEXT = self._prev_active
        if self._initialized_distributed:
            # destroy on success, abort-equivalent on exception
            # (reference cuml_context.py:150-167)
            try:
                jax.distributed.shutdown()
            except Exception:
                pass
        if self.rendezvous is not None and exc_type is None:
            self.rendezvous.barrier()
        return False
