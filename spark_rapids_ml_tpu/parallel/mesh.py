#
# Device-mesh helpers: the substrate every solver runs on.
#
# Design: all solvers are SPMD programs over a 1-D mesh axis `rows` (data
# parallelism over row blocks — the reference's only data-plane parallelism, see
# SURVEY.md §2.4). Row counts are padded to a multiple of the mesh size and the
# padding is neutralized with zero sample-weights, which unifies the reference's
# ragged `parts_rank_size` handling (cuML MG accepts ragged blocks; SPMD XLA
# wants equal ones) with `weightCol` support.
#
from __future__ import annotations

import contextlib
from typing import Optional, Tuple, Union

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ROWS_AXIS = "rows"

# Device-resolution hook: which devices the framework runs on. Overridable for
# tests (virtual multi-device CPU mesh while a real TPU backend is registered)
# and for pinning a subset of chips. Resolution order: explicit override ->
# SRML_PLATFORM env var -> jax.devices().
_DEVICE_OVERRIDE: Optional[list] = None


def set_devices(devices_or_platform: Union[str, list, None]) -> None:
    """Override the framework's device pool ('cpu', 'tpu', a device list, or None)."""
    global _DEVICE_OVERRIDE
    if devices_or_platform is None:
        _DEVICE_OVERRIDE = None
    elif isinstance(devices_or_platform, str):
        _DEVICE_OVERRIDE = list(jax.devices(devices_or_platform))
    else:
        _DEVICE_OVERRIDE = list(devices_or_platform)


def default_devices() -> list:
    import os

    if _DEVICE_OVERRIDE is not None:
        return _DEVICE_OVERRIDE
    platform = os.environ.get("SRML_PLATFORM")
    if platform:
        return list(jax.devices(platform))
    return list(jax.devices())


def default_local_device():
    """First framework device ADDRESSABLE by this process. Transform of a
    process-local batch must never target another process's device (under
    multi-process SPMD `default_devices()[0]` is rank 0's device — placing
    there from rank 1 deadlocks)."""
    local = [d for d in default_devices() if d.process_index == jax.process_index()]
    return local[0] if local else jax.local_devices()[0]


def get_mesh(num_workers: Optional[int] = None, devices=None) -> Mesh:
    """Build a 1-D `rows` mesh over the first `num_workers` visible devices.

    In multi-process (multi-host) runs `jax.devices()` is the global device list,
    so the same call yields the global mesh on every process — the direct analog
    of the reference's NCCL clique of `num_workers` ranks
    (reference common/cuml_context.py:36-148).
    """
    if devices is None:
        devices = default_devices()
    if num_workers is None:
        num_workers = len(devices)
    if num_workers > len(devices):
        raise ValueError(
            f"num_workers={num_workers} exceeds visible devices ({len(devices)}); "
            "set num_workers or start more processes"
        )
    return Mesh(np.asarray(devices[:num_workers]), (ROWS_AXIS,))


def row_sharding(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """NamedSharding that shards axis 0 over `rows` and replicates the rest."""
    return NamedSharding(mesh, P(ROWS_AXIS, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


@contextlib.contextmanager
def dtype_scope(dtype, matmul_precision: str = "float32"):
    """Numerics context for the framework's own computations: real f64 when
    asked for, and a PER-SOLVER matmul precision.

    - JAX's default `jax_enable_x64=False` silently downcasts f64 to f32; a user
      who passed ``float32_inputs=False`` asked for double precision (the
      reference supports f64 end-to-end; SURVEY.md §7 'float64 parity'). The
      flag is enabled via the scoped context so the user's own JAX code keeps
      its default semantics.
    - TPU matmuls default to one-pass bf16 on the MXU (~3 decimal digits) —
      fine for neural nets, wrong for most classical ML. Each solver picks the
      cheapest precision that preserves its numeric contract via the estimator's
      `_matmul_precision` attribute (plumbed here by core._call_fit_func):

        * ``"float32"`` (default, 6-pass MXU): CPU-equivalent f32 accuracy.
          Required by kNN/DBSCAN distance expansions (sklearn-exact parity
          asserted in tests; raw bf16 shows ~2% distance error on a v5e chip)
          and used for covariance/gram/L-BFGS solvers where parity tolerances
          are tight.
        * ``"BF16_BF16_F32_X3"`` (3-pass MXU, ~2x the f32 throughput): used by
          KMeans — Lloyd's argmin assignment tolerates the ~1e-6 relative
          error of the 3-pass expansion, and the center-update reductions are
          plain f32 sums (no matmul), so inertia/center parity holds while the
          dominant distance matmul runs twice as fast.

      CPU/GPU backends ignore the hint (always full f32), so test parity on the
      virtual CPU mesh is unaffected either way.
    """
    with contextlib.ExitStack() as stack:
        if np.dtype(dtype) == np.float64 and not jax.config.jax_enable_x64:
            stack.enter_context(jax.enable_x64(True))  # jax config State: scoped context
        if np.dtype(dtype) == np.float64:
            matmul_precision = "float32"  # f64 runs don't want a reduced-pass MXU mode
        stack.enter_context(jax.default_matmul_precision(matmul_precision))
        yield


def pad_rows(x: np.ndarray, multiple: int) -> Tuple[np.ndarray, int]:
    """Zero-pad axis 0 of `x` to a multiple of `multiple`; returns (padded, n_valid)."""
    n = x.shape[0]
    rem = (-n) % multiple
    if rem == 0:
        return x, n
    pad_widths = [(0, rem)] + [(0, 0)] * (x.ndim - 1)
    return np.pad(x, pad_widths), n


def make_global_rows(
    mesh: Mesh,
    x: np.ndarray,
    *,
    weights: Optional[np.ndarray] = None,
    local_rows_target: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array, int]:
    """Place a host row-block on the mesh as a row-sharded global array.

    Pads rows and returns ``(X, w, n_valid)`` where `w` is a row-weight vector
    with zeros on padding rows (and the user's sample weights elsewhere).
    Solvers MUST use `w` for any per-row reduction so padding never
    contaminates results.

    Single-controller path: `jax.device_put` with a NamedSharding splits the
    host array (padded to a multiple of the mesh size) across local devices.
    Under multi-process SPMD, `x` is this PROCESS's local block; every process
    pads its block to `local_rows_target` rows (the rendezvous-agreed common
    local size — processes hold ragged row counts, SPMD XLA wants equal
    shards) and the global array is assembled from the per-process shards.
    """
    n_dev = mesh.devices.size
    x = np.ascontiguousarray(x)
    if weights is None:
        weights = np.ones(x.shape[0], dtype=x.dtype if x.dtype.kind == "f" else np.float32)
    weights = np.asarray(weights)

    if jax.process_count() == 1:
        xp, n_valid = pad_rows(x, n_dev)
        wp, _ = pad_rows(np.asarray(weights, dtype=xp.dtype if xp.dtype.kind == "f" else np.float32), n_dev)
        if n_dev == 1:
            # plain placement: a committed 1-device NamedSharding makes Shardy
            # insert a full input-resharding copy of X in consumer programs
            # (measured 11 GiB at the 1M x 3k benchmark shape)
            dev = mesh.devices.flatten()[0]
            X = jax.device_put(xp, dev)
            w = jax.device_put(wp, dev)
        else:
            X = jax.device_put(xp, row_sharding(mesh, xp.ndim))
            w = jax.device_put(wp, row_sharding(mesh, 1))
    else:  # multi-process: x is this process's local block
        from jax.experimental import multihost_utils

        n_local_dev = jax.local_device_count()
        if local_rows_target is None:
            local_rows_target = -(-x.shape[0] // n_local_dev) * n_local_dev
        if local_rows_target < x.shape[0] or local_rows_target % n_local_dev:
            raise ValueError(
                f"local_rows_target={local_rows_target} must cover the {x.shape[0]} local "
                f"rows and divide by the {n_local_dev} local devices"
            )
        n_valid = x.shape[0]
        xp = np.pad(x, [(0, local_rows_target - n_valid)] + [(0, 0)] * (x.ndim - 1))
        wp = np.pad(
            np.asarray(weights, dtype=xp.dtype if xp.dtype.kind == "f" else np.float32),
            (0, local_rows_target - n_valid),
        )
        X = multihost_utils.host_local_array_to_global_array(xp, mesh, P(ROWS_AXIS))
        w = multihost_utils.host_local_array_to_global_array(wp, mesh, P(ROWS_AXIS))
    return X, w, n_valid
