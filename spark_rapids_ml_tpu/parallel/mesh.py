#
# Device-mesh helpers: the substrate every solver runs on.
#
# Design: all solvers are SPMD programs over a 1-D mesh axis `rows` (data
# parallelism over row blocks — the reference's only data-plane parallelism, see
# SURVEY.md §2.4). Row counts are padded to a multiple of the mesh size and the
# padding is neutralized with zero sample-weights, which unifies the reference's
# ragged `parts_rank_size` handling (cuML MG accepts ragged blocks; SPMD XLA
# wants equal ones) with `weightCol` support.
#
from __future__ import annotations

import contextlib
import contextvars
from typing import Dict, Optional, Sequence, Tuple, Union

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import telemetry
from ..errors import MeshTopologyError

try:  # jax >= 0.6 exports shard_map at the top level
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # jax 0.4.x: experimental home, same keyword signature
    from jax.experimental.shard_map import shard_map  # noqa: F401

ROWS_AXIS = "rows"
# outer axis of a hierarchical mesh: one step per jax.distributed process
# group (DCN hops cross process boundaries; ICI stays inside one group)
DCN_AXIS = "dcn"


def pcast_varying(t, axis_name: str):
    """Type `t` as varying over `axis_name` inside a shard_map body — the
    newer-jax `lax.pcast(..., to="varying")` vma typing. On jax builds without
    `pcast` (<= 0.4.x shard_map) there is no varying-axes type system and the
    value is already per-shard, so this is the identity."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(t, axis_name, to="varying")
    return t

# Device-resolution hook: which devices the framework runs on. Overridable for
# tests (virtual multi-device CPU mesh while a real TPU backend is registered)
# and for pinning a subset of chips. Resolution order: explicit override ->
# SRML_PLATFORM env var -> jax.devices().
_DEVICE_OVERRIDE: Optional[list] = None


def set_devices(devices_or_platform: Union[str, list, None]) -> None:
    """Override the framework's device pool ('cpu', 'tpu', a device list, or None)."""
    global _DEVICE_OVERRIDE
    if devices_or_platform is None:
        _DEVICE_OVERRIDE = None
    elif isinstance(devices_or_platform, str):
        _DEVICE_OVERRIDE = list(jax.devices(devices_or_platform))
    else:
        _DEVICE_OVERRIDE = list(devices_or_platform)


# Context-local chip pinning: the sub-mesh placement engine runs co-admitted
# jobs on DISJOINT chip sets concurrently, so the pin must be per
# thread/task — `set_devices` is process-global and would race. The scope is
# consulted FIRST by `default_devices()`: a job inside `chip_scope(chips)`
# sees only its claimed chips, so every downstream mesh/placement/capacity
# call lands on the claimed sub-mesh without threading a device list.
_CHIP_SCOPE: "contextvars.ContextVar[Optional[tuple]]" = contextvars.ContextVar(
    "srml_chip_scope", default=None
)


@contextlib.contextmanager
def chip_scope(devices: Sequence):
    """Pin `default_devices()` to an explicit chip set for the duration of
    the with-block, context-locally (threads/tasks see their own pin). The
    scheduler wraps each co-admitted job's fit in the job's claimed chip
    set; tests use it to emulate a carved sub-mesh."""
    token = _CHIP_SCOPE.set(tuple(devices))
    try:
        yield
    finally:
        _CHIP_SCOPE.reset(token)


def current_chip_scope() -> Optional[Tuple]:
    """The enclosing `chip_scope` pin, or None (whole pool)."""
    return _CHIP_SCOPE.get()


def default_devices() -> list:
    import os

    scoped = _CHIP_SCOPE.get()
    if scoped is not None:
        return list(scoped)
    if _DEVICE_OVERRIDE is not None:
        return _DEVICE_OVERRIDE
    platform = os.environ.get("SRML_PLATFORM")
    if platform:
        return list(jax.devices(platform))
    return list(jax.devices())


def default_local_device():
    """First framework device ADDRESSABLE by this process. Transform of a
    process-local batch must never target another process's device (under
    multi-process SPMD `default_devices()[0]` is rank 0's device — placing
    there from rank 1 deadlocks)."""
    local = [d for d in default_devices() if d.process_index == jax.process_index()]
    return local[0] if local else jax.local_devices()[0]


def get_mesh(num_workers: Optional[int] = None, devices=None) -> Mesh:
    """Build a 1-D `rows` mesh over the first `num_workers` visible devices.

    In multi-process (multi-host) runs `jax.devices()` is the global device list,
    so the same call yields the global mesh on every process — the direct analog
    of the reference's NCCL clique of `num_workers` ranks
    (reference common/cuml_context.py:36-148).
    """
    if devices is None:
        devices = default_devices()
    if num_workers is None:
        num_workers = len(devices)
    num_workers = int(num_workers)
    if num_workers <= 0:
        raise MeshTopologyError(
            f"num_workers={num_workers} must be positive",
            requested=num_workers, available=len(devices),
        )
    if num_workers > len(devices):
        raise MeshTopologyError(
            f"num_workers={num_workers} exceeds visible devices "
            f"({len(devices)}); set num_workers or start more processes",
            requested=num_workers, available=len(devices),
        )
    if len(devices) % num_workers != 0:
        # an uneven split used to surface as an opaque numpy reshape error
        # deep inside row padding; refuse typed at mesh construction instead
        raise MeshTopologyError(
            f"num_workers={num_workers} does not divide the "
            f"{len(devices)}-device pool evenly; pick a worker count that "
            "divides the device count (or carve an explicit sub-mesh with "
            "submesh()/chip_scope())",
            requested=num_workers, available=len(devices),
        )
    return Mesh(np.asarray(devices[:num_workers]), (ROWS_AXIS,))


def build_mesh(
    topology: Optional[Dict[str, int]] = None, devices=None
) -> Mesh:
    """Build the framework mesh, hierarchically when asked.

    ``topology=None`` (default) is the flat 1-D `rows` mesh over every
    visible device — exactly `get_mesh()`. A topology dict composes an ICI
    axis with a DCN axis: ``{"dcn": D, "rows": R}`` builds a 2-D
    ``(dcn, rows)`` `jax.sharding.Mesh` whose outer axis steps across
    `jax.distributed` process groups (devices are stably grouped by
    `process_index`, so each DCN row is one host's ICI-connected chips) and
    whose inner axis is the per-group chip count. Either axis may be 0/absent
    ("auto"): `dcn` defaults to the process-group count, `rows` to the
    remaining factor. The axis product must cover the pool exactly — a
    mismatch raises the typed `MeshTopologyError` naming both sides.

    Fold grids vmap under `shard_map` over the inner `rows` axis of the
    result (or of a `submesh()` carved from it); collectives along `dcn`
    cross the data-center network and stay in the control plane."""
    if topology is None:
        # the config knob is the deployment-wide default; an explicit
        # argument (even {}) wins
        from ..core import config

        topology = config.get("mesh_topology")
    if devices is None:
        devices = default_devices()
    devices = list(devices)
    if not topology:
        return get_mesh(len(devices), devices)
    unknown = set(topology) - {DCN_AXIS, ROWS_AXIS}
    if unknown:
        raise MeshTopologyError(
            f"unknown topology axes {sorted(unknown)}; expected "
            f"{DCN_AXIS!r} and/or {ROWS_AXIS!r}",
            topology={k: int(v) for k, v in topology.items()},
        )
    # stable process grouping: jax.devices() is process-ordered already, but
    # an explicit device list may not be — sort stably so each DCN row holds
    # one process group's ICI-connected chips
    devices.sort(key=lambda d: int(getattr(d, "process_index", 0)))
    n_groups = len({int(getattr(d, "process_index", 0)) for d in devices})
    dcn = int(topology.get(DCN_AXIS) or 0)
    rows = int(topology.get(ROWS_AXIS) or 0)
    if dcn <= 0 and rows <= 0:
        dcn = max(1, n_groups)
    if dcn <= 0:
        dcn = len(devices) // rows if rows and len(devices) % rows == 0 else 0
    if rows <= 0:
        rows = len(devices) // dcn if dcn and len(devices) % dcn == 0 else 0
    if dcn <= 0 or rows <= 0 or dcn * rows != len(devices):
        raise MeshTopologyError(
            "topology axis product must cover the device pool exactly",
            requested=(dcn * rows) if dcn > 0 and rows > 0 else None,
            available=len(devices),
            topology={DCN_AXIS: dcn, ROWS_AXIS: rows},
        )
    if telemetry.enabled():
        telemetry.registry().inc("mesh.hierarchical_builds")
    grid = np.empty((dcn, rows), dtype=object)
    for i, d in enumerate(devices):
        grid[i // rows, i % rows] = d
    return Mesh(grid, (DCN_AXIS, ROWS_AXIS))


def submesh(mesh: Mesh, chips: Union[int, Sequence]) -> Mesh:
    """Carve a CONTIGUOUS chip subset out of `mesh` as a 1-D `rows`
    sub-mesh — the unit the 2-D scheduler places fits, serving replicas,
    and sweep shards on, so disjoint carves own disjoint chips concurrently.

    `chips` is an int (the first N chips in mesh order) or an explicit
    sequence of mesh-order indices / device objects. Contiguity (in the
    parent's flattened order, i.e. ICI-neighbor runs within a DCN row) is
    enforced: a gapped carve raises `MeshTopologyError` — scattered chips
    would silently route ICI collectives over DCN."""
    flat = list(mesh.devices.flatten())
    if isinstance(chips, (int, np.integer)):
        n = int(chips)
        if n <= 0 or n > len(flat):
            raise MeshTopologyError(
                f"submesh: cannot carve {n} chips from a "
                f"{len(flat)}-chip mesh",
                requested=n, available=len(flat),
            )
        picked = flat[:n]
    else:
        by_id = {id(d): i for i, d in enumerate(flat)}
        idx = []
        for c in chips:
            if isinstance(c, (int, np.integer)):
                i = int(c)
                if i < 0 or i >= len(flat):
                    raise MeshTopologyError(
                        f"submesh: chip index {i} out of range",
                        requested=i, available=len(flat),
                    )
            else:
                if id(c) not in by_id:
                    raise MeshTopologyError(
                        f"submesh: device {c} is not part of the parent mesh",
                        available=len(flat),
                    )
                i = by_id[id(c)]
            idx.append(i)
        if not idx:
            raise MeshTopologyError(
                "submesh: empty chip set", requested=0, available=len(flat)
            )
        idx.sort()
        if len(set(idx)) != len(idx) or idx[-1] - idx[0] + 1 != len(idx):
            raise MeshTopologyError(
                f"submesh: chip set {idx} is not a contiguous run in the "
                "parent mesh order",
                requested=len(idx), available=len(flat),
            )
        picked = [flat[i] for i in idx]
    if telemetry.enabled():
        telemetry.registry().inc("mesh.submesh_carves")
    return Mesh(np.asarray(picked), (ROWS_AXIS,))


def survivor_mesh(mesh: Mesh, dead_process_indices) -> Mesh:
    """Rebuild a mesh over the devices NOT owned by the dead processes — the
    re-sharding half of elastic recovery: under GSPMD a rank loss is a mesh +
    placement change, not a solver rewrite (docs/robustness.md "Elastic
    recovery"). Raises when no devices survive.

    Composes with the hierarchical/sub-mesh substrate: a 1-D mesh (whole
    pool OR a `submesh()` carve — a sweep shard that loses a host re-meshes
    its own sub-mesh, not the whole pool) survives as a 1-D `rows` mesh over
    the remaining chips; a 2-D `(dcn, rows)` mesh keeps its hierarchy when
    whole DCN rows die, and degrades to the flat 1-D survivors otherwise
    (a ragged 2-D grid is not a mesh)."""
    dead = {int(p) for p in dead_process_indices}
    devices = [d for d in mesh.devices.flatten() if int(d.process_index) not in dead]
    if not devices:
        raise MeshTopologyError(
            "survivor_mesh: no devices remain after excluding processes "
            f"{sorted(dead)}",
            requested=0, available=0,
        )
    if telemetry.enabled():
        telemetry.registry().inc("recovery.mesh_rebuilds")
    if mesh.devices.ndim == 2:
        rows = [
            list(row)
            for row in mesh.devices
            if all(int(d.process_index) not in dead for d in row)
        ]
        if rows and len(rows) * len(rows[0]) == len(devices):
            # only whole DCN rows died: the hierarchy survives intact
            grid = np.empty((len(rows), len(rows[0])), dtype=object)
            for i, row in enumerate(rows):
                for j, d in enumerate(row):
                    grid[i, j] = d
            return Mesh(grid, mesh.axis_names)
    return Mesh(np.asarray(devices), (ROWS_AXIS,))


def row_sharding(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """NamedSharding that shards axis 0 over `rows` and replicates the rest."""
    return NamedSharding(mesh, P(ROWS_AXIS, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


_COMPILE_CACHE_DIR: Optional[str] = None  # dir currently wired into jax, if any


def ensure_compilation_cache() -> bool:
    """Point XLA's PERSISTENT compilation cache at
    ``core.config["compilation_cache_dir"]`` (seeded from
    ``SRML_COMPILE_CACHE_DIR``), so compiled programs survive process
    restarts — a transform fleet's bucket-ladder programs and a sweep's
    batched solver compile once per cluster, not once per process. Called
    from the fit and transform entry points; re-pointing the config dir
    takes effect on the next call. Returns whether a cache dir is active."""
    global _COMPILE_CACHE_DIR
    from ..core import config

    path = config.get("compilation_cache_dir") or None
    if path == _COMPILE_CACHE_DIR:
        return path is not None
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        if path is not None:
            # default thresholds skip sub-second programs — the dispatch-bound
            # serving shapes this cache exists for; persist everything
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
            try:
                jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
            except Exception:  # older jax: knob absent, default is fine
                pass
    except Exception as e:  # pragma: no cover - jax build without the cache
        from ..utils import get_logger

        get_logger("mesh").warning(
            "could not enable the persistent compilation cache at %r (%s: %s)",
            path, type(e).__name__, e,
        )
        return False
    _COMPILE_CACHE_DIR = path
    return path is not None


_PRECISION_SUPPORT: dict = {}


def _matmul_precision_supported(precision: str, platform: str) -> bool:
    """Probe whether `platform`'s dot_general accepts `precision` by lowering
    a tiny jitted dot against an input committed to that platform's device 0
    (jit compiles for the committed device, not the default backend). Only
    DEFINITIVE verdicts are cached: a backend rejecting the mode raises
    ValueError; any other error is a transient probe failure — fall back to
    float32 for this call but re-probe next time instead of pinning the
    process to the fallback forever."""
    key = (precision, platform)
    if key in _PRECISION_SUPPORT:
        return _PRECISION_SUPPORT[key]
    # validate the NAME first, outside the probe: a typo'd precision string
    # raises here (config-level ValueError) and must surface to the caller,
    # not be cached as "backend rejects this mode"
    with jax.default_matmul_precision(precision):
        pass
    try:
        x = jax.device_put(np.zeros((2, 2), np.float32), jax.devices(platform)[0])
        with jax.default_matmul_precision(precision):
            jax.jit(lambda a: a @ a).lower(x).compile()
        _PRECISION_SUPPORT[key] = True
    except ValueError:  # "precision ... is not supported": definitive rejection
        _PRECISION_SUPPORT[key] = False
    except Exception as e:  # transient (OOM/backend hiccup): don't cache
        from ..utils import get_logger

        get_logger("mesh").warning(
            "matmul precision probe for %r on %s failed transiently (%s: %s); "
            "using float32 for this call", precision, platform, type(e).__name__, e,
        )
        return False
    return _PRECISION_SUPPORT[key]


def effective_matmul_precision(precision: str) -> str:
    """`precision`, downgraded to plain "float32" when the FRAMEWORK devices'
    backend rejects it. Reduced-pass MXU algorithm presets
    ("BF16_BF16_F32_X3", ...) are TPU modes; CPU lowering on older jax builds
    raises for them instead of ignoring the hint. Probed per (precision,
    platform) — the framework's device pool can differ from jax's default
    backend (set_devices('cpu') virtual mesh alongside a registered TPU)."""
    if precision in ("float32", "highest", "default"):
        return precision  # universally supported: skip the probe compile
    platform = default_devices()[0].platform
    if _matmul_precision_supported(precision, platform):
        return precision
    return "float32"


@contextlib.contextmanager
def dtype_scope(dtype, matmul_precision: str = "float32"):
    """Numerics context for the framework's own computations: real f64 when
    asked for, and a PER-SOLVER matmul precision.

    - JAX's default `jax_enable_x64=False` silently downcasts f64 to f32; a user
      who passed ``float32_inputs=False`` asked for double precision (the
      reference supports f64 end-to-end; SURVEY.md §7 'float64 parity'). The
      flag is enabled via the scoped context so the user's own JAX code keeps
      its default semantics.
    - TPU matmuls default to one-pass bf16 on the MXU (~3 decimal digits) —
      fine for neural nets, wrong for most classical ML. Each solver picks the
      cheapest precision that preserves its numeric contract via the estimator's
      `_matmul_precision` attribute (plumbed here by core._call_fit_func):

        * ``"float32"`` (default, 6-pass MXU): CPU-equivalent f32 accuracy.
          Required by kNN/DBSCAN distance expansions (sklearn-exact parity
          asserted in tests; raw bf16 shows ~2% distance error on a v5e chip)
          and used for covariance/gram/L-BFGS solvers where parity tolerances
          are tight.
        * ``"BF16_BF16_F32_X3"`` (3-pass MXU, ~2x the f32 throughput): used by
          KMeans — Lloyd's argmin assignment tolerates the ~1e-6 relative
          error of the 3-pass expansion, and the center-update reductions are
          plain f32 sums (no matmul), so inertia/center parity holds while the
          dominant distance matmul runs twice as fast.

      CPU/GPU backends ignore the hint (always full f32), so test parity on the
      virtual CPU mesh is unaffected either way.
    """
    with contextlib.ExitStack() as stack:
        if np.dtype(dtype) == np.float64 and not jax.config.jax_enable_x64:
            # scoped x64: top-level jax.enable_x64 on newer jax, the
            # experimental home on 0.4.x
            _enable_x64 = getattr(jax, "enable_x64", None)
            if _enable_x64 is None:
                from jax.experimental import enable_x64 as _enable_x64
            stack.enter_context(_enable_x64(True))  # jax config State: scoped context
        if np.dtype(dtype) == np.float64:
            matmul_precision = "float32"  # f64 runs don't want a reduced-pass MXU mode
        stack.enter_context(
            jax.default_matmul_precision(effective_matmul_precision(matmul_precision))
        )
        yield


def pad_rows(x: np.ndarray, multiple: int) -> Tuple[np.ndarray, int]:
    """Zero-pad axis 0 of `x` to a multiple of `multiple`; returns (padded, n_valid)."""
    n = x.shape[0]
    rem = (-n) % multiple
    if rem == 0:
        return x, n
    pad_widths = [(0, rem)] + [(0, 0)] * (x.ndim - 1)
    return np.pad(x, pad_widths), n


def bucket_size(n: int, *, multiple: int = 1, min_rows: int = 256, cap: Optional[int] = None) -> int:
    """Row count of the bucket that batch size `n` pads up to.

    Serving pads every transform batch to a small GEOMETRIC ladder of row
    buckets (min_rows, 2·min_rows, 4·min_rows, ...) instead of running the
    exact batch shape: a jitted `predict` then compiles once per BUCKET, not
    once per distinct tail shape — on a TPU backend each avoided compile is
    tens of seconds. Every rung is rounded up to `multiple` (the mesh shard
    count on the distributed path), and the ladder is capped at `cap`
    (aligned up) so a near-full tail batch reuses the full-batch program
    instead of minting one more rung."""
    if multiple < 1:
        multiple = 1
    b = max(min_rows, multiple)
    b = -(-b // multiple) * multiple
    cap_aligned = None
    if cap is not None:
        cap_aligned = -(-max(cap, multiple) // multiple) * multiple
        if n >= cap_aligned:
            return cap_aligned
    while b < n:
        b = -(-(b * 2) // multiple) * multiple
    if cap_aligned is not None:
        b = min(b, cap_aligned)
    return b


def bucket_ladder(
    max_rows: int, *, multiple: int = 1, min_rows: int = 256, cap: Optional[int] = None
) -> list:
    """Every distinct rung `bucket_size` can return for batch sizes
    1..max_rows — the set of predict-program shapes serving traffic in that
    range can ever dispatch, and therefore exactly what the serving plane's
    load-time prewarm compiles (docs/serving.md). Derived by WALKING
    `bucket_size` itself (next probe = previous rung + 1), so the ladder can
    never drift from the padding function that defines it."""
    max_rows = max(1, int(max_rows))
    rungs: list = []
    n = 1
    while True:  # blocking-ok: pure arithmetic walk — rungs strictly grow until max_rows/cap, no waiting
        b = bucket_size(n, multiple=multiple, min_rows=min_rows, cap=cap)
        if rungs and b <= rungs[-1]:
            break  # the cap rung repeats for every larger n — ladder is done
        rungs.append(b)
        if b >= max_rows:
            break
        n = b + 1
    return rungs


def bucket_rows(
    x: np.ndarray, *, multiple: int = 1, min_rows: int = 256, cap: Optional[int] = None
) -> Tuple[np.ndarray, int]:
    """Zero-pad axis 0 of `x` up to its `bucket_size` rung; returns
    (padded, n_valid). THE one sanctioned padding entry point for
    transform/serving code (the ci/analysis gate forbids raw `pad_rows` there): callers
    slice every output back to `n_valid` rows."""
    b = bucket_size(x.shape[0], multiple=multiple, min_rows=min_rows, cap=cap)
    n = x.shape[0]
    if b == n:
        return x, n
    pad_widths = [(0, b - n)] + [(0, 0)] * (x.ndim - 1)
    return np.pad(x, pad_widths), n


def shard_row_slices(x: np.ndarray, n_dev: int) -> Tuple[list, int]:
    """Cut a host row block into `n_dev` equal per-shard pieces.

    Returns ``(pieces, n_pad)``: `n_dev` arrays of ``n_pad // n_dev`` rows
    each, where all but the tail shard are ZERO-COPY views of `x` — only the
    shard that crosses the valid-row boundary is padded (one small copy)
    instead of re-materializing the whole padded block the way
    ``pad_rows`` + monolithic placement did (~1x dataset bytes saved).
    """
    n = x.shape[0]
    n_pad = -(-n // n_dev) * n_dev  # 0 rows stay 0 rows (pad_rows parity)
    per = n_pad // n_dev
    pieces = []
    for i in range(n_dev):
        lo = i * per
        hi = max(lo, min(lo + per, n))
        piece = x[lo:hi]
        if piece.shape[0] < per:  # tail shard (or pure padding when n < n_pad)
            piece = np.pad(piece, [(0, per - piece.shape[0])] + [(0, 0)] * (x.ndim - 1))
        pieces.append(piece)
    return pieces, n_pad


def place_row_shards(mesh: Mesh, x: np.ndarray) -> jax.Array:
    """Place a host row block on the mesh shard-by-shard.

    The old path padded the whole block (full host copy) and handed one
    monolithic buffer to `jax.device_put`, staging a third copy and
    serializing the H2D transfer. Here each device's row range is sliced as a
    view, only the tail shard is padded, and ONE batched `device_put` call
    dispatches all per-device transfers back-to-back so they overlap; the
    global array is assembled with `jax.make_array_from_single_device_arrays`
    — numerically identical to the monolithic placement (equality asserted in
    tests/test_ingest.py) at ~1/3 the peak host footprint.
    """
    devices = list(mesh.devices.flatten())
    pieces, n_pad = shard_row_slices(x, len(devices))
    if telemetry.enabled():
        reg = telemetry.registry()
        reg.inc("placement.device_put_calls")
        reg.inc("placement.shards", len(pieces))
        reg.inc("placement.bytes", sum(p.nbytes for p in pieces))
        reg.inc("placement.rows_padded", n_pad - x.shape[0])
    shards = jax.device_put(pieces, devices)
    return jax.make_array_from_single_device_arrays(
        (n_pad,) + x.shape[1:], row_sharding(mesh, x.ndim), shards
    )


def place_rows(
    mesh: Mesh, x: np.ndarray, *, local_rows_target: Optional[int] = None
) -> jax.Array:
    """X-only `make_global_rows`: identical row layout/padding, no weight
    vector built or placed — for callers laying out SEVERAL per-row arrays
    that share one weight vector (ELL values+indices+labels)."""
    x = np.ascontiguousarray(x)
    if jax.process_count() > 1:  # multi-process SPMD: x is this rank's block
        from jax.experimental import multihost_utils

        n_local_dev = jax.local_device_count()
        if local_rows_target is None:
            local_rows_target = -(-x.shape[0] // n_local_dev) * n_local_dev
        if local_rows_target < x.shape[0] or local_rows_target % n_local_dev:
            raise ValueError(
                f"local_rows_target={local_rows_target} must cover the {x.shape[0]} local "
                f"rows and divide by the {n_local_dev} local devices"
            )
        xp = np.pad(
            x, [(0, local_rows_target - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
        )
        if telemetry.enabled():
            reg = telemetry.registry()
            # this branch performs no jax.device_put of its own — the
            # multihost assembly owns the transfer, counted separately
            reg.inc("placement.global_assembly_calls")
            reg.inc("placement.bytes", xp.nbytes)
            reg.inc("placement.rows_padded", local_rows_target - x.shape[0])
        return multihost_utils.host_local_array_to_global_array(xp, mesh, P(ROWS_AXIS))
    if mesh.devices.size == 1:
        if telemetry.enabled():
            reg = telemetry.registry()
            reg.inc("placement.device_put_calls")
            reg.inc("placement.bytes", x.nbytes)
        return jax.device_put(x, mesh.devices.flatten()[0])
    return place_row_shards(mesh, x)


def stream_place_blocks(mesh: Mesh, host_blocks):
    """Double-buffered host->HBM chunk pipeline — the out-of-core fits'
    transfer engine (docs/robustness.md "Memory safety").

    `host_blocks` is an iterator of dicts of SAME-row-count host arrays (one
    streaming chunk: features + labels + weights + ...); each is placed
    row-sharded over `mesh` via `place_rows` (numpy's zero tail-padding makes
    padded weight rows weightless for free) and yielded as the same-keyed
    dict of device arrays. The pipeline dispatches chunk N+1's `device_put`
    BEFORE yielding chunk N, so the H2D transfer of the next chunk is in
    flight while the caller computes on the current one — two chunks resident
    at once, never the dataset.

    Telemetry (per drained pass): `ingest.stream_chunks`/`ingest.stream_rows`
    counters, a `device.{peak_,}bytes_in_use` watermark sample at every chunk
    boundary (so out-of-core peaks are visible, not just post-layout/post-
    solve ones), and the `ingest.overlap_fraction` gauge — the fraction of
    prefetched chunks whose transfer had COMPLETED by the time the caller
    finished computing on the previous chunk, probed via `Array.is_ready`
    where the backend exposes it (dispatch-order fallback otherwise: the
    transfer was at least in flight during the compute). (n-1)/n when fully
    pipelined; the acceptance assertion is simply > 0 on any multi-chunk
    fit, and ~0 there means the transfer is slower than the compute — a
    broken (serialized) pipeline, or chunks too small to amortize."""
    it = iter(host_blocks)

    def _place(d: dict) -> dict:
        return {k: place_rows(mesh, np.ascontiguousarray(v)) for k, v in d.items()}

    def _transfer_done(placed: dict) -> bool:
        try:
            return all(bool(a.is_ready()) for a in placed.values())
        except Exception:
            return True  # no is_ready on this backend: dispatch-order fallback

    try:
        cur_host = next(it)
    except StopIteration:
        return
    total = overlapped = 0
    rows = 0
    cur = _place(cur_host)
    rows += next(iter(cur_host.values())).shape[0]
    for nxt_host in it:
        # dispatch N+1 BEFORE handing N to the caller: the generator resumes
        # after the yield only once the caller finished computing on chunk N,
        # so the prefetched transfer runs concurrently with that compute
        nxt = _place(nxt_host)
        rows += next(iter(nxt_host.values())).shape[0]
        total += 1
        telemetry.record_device_memory()  # out-of-core watermark sample
        yield cur
        if _transfer_done(nxt):  # finished while the caller computed
            overlapped += 1
        cur = nxt
    total += 1
    telemetry.record_device_memory()
    yield cur
    if telemetry.enabled():
        reg = telemetry.registry()
        reg.inc("ingest.stream_chunks", total)
        reg.inc("ingest.stream_rows", rows)
        reg.gauge("ingest.overlap_fraction", overlapped / total)


def make_global_rows(
    mesh: Mesh,
    x: np.ndarray,
    *,
    weights: Optional[np.ndarray] = None,
    local_rows_target: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array, int]:
    """Place a host row-block on the mesh as a row-sharded global array.

    Pads rows and returns ``(X, w, n_valid)`` where `w` is a row-weight vector
    with zeros on padding rows (and the user's sample weights elsewhere).
    Solvers MUST use `w` for any per-row reduction so padding never
    contaminates results.

    Single-controller path: the host block is cut into per-device row ranges
    (zero-copy views, tail shard padded) and placed shard-by-shard
    (`place_row_shards`) — transfers dispatch back-to-back and no whole-block
    padded copy is ever made. Under multi-process SPMD, `x` is this PROCESS's
    local block; every process
    pads its block to `local_rows_target` rows (the rendezvous-agreed common
    local size — processes hold ragged row counts, SPMD XLA wants equal
    shards) and the global array is assembled from the per-process shards.
    """
    n_dev = mesh.devices.size
    x = np.ascontiguousarray(x)
    if weights is None:
        weights = np.ones(x.shape[0], dtype=x.dtype if x.dtype.kind == "f" else np.float32)
    weights = np.asarray(weights)

    if jax.process_count() == 1:
        n_valid = x.shape[0]
        w_host = np.asarray(weights, dtype=x.dtype if x.dtype.kind == "f" else np.float32)
        if n_dev == 1:
            # plain placement: a committed 1-device NamedSharding makes Shardy
            # insert a full input-resharding copy of X in consumer programs
            # (measured 11 GiB at the 1M x 3k benchmark shape)
            dev = mesh.devices.flatten()[0]
            if telemetry.enabled():
                reg = telemetry.registry()
                reg.inc("placement.device_put_calls", 2)
                reg.inc("placement.bytes", x.nbytes + w_host.nbytes)
            X = jax.device_put(x, dev)
            w = jax.device_put(w_host, dev)
        else:
            X = place_row_shards(mesh, x)
            w = place_row_shards(mesh, w_host)
    else:  # multi-process: x is this process's local block
        n_local_dev = jax.local_device_count()
        if local_rows_target is None:
            local_rows_target = -(-x.shape[0] // n_local_dev) * n_local_dev
        n_valid = x.shape[0]
        X = place_rows(mesh, x, local_rows_target=local_rows_target)
        w = place_rows(
            mesh,
            np.asarray(weights, dtype=x.dtype if x.dtype.kind == "f" else np.float32),
            local_rows_target=local_rows_target,
        )
    return X, w, n_valid
