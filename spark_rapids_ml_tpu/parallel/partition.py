#
# Partition layout bookkeeping — the calling-convention analog of the reference's
# `PartitionDescriptor` (reference utils.py:173-210), which allGathers
# `(rank, rows)` pairs so every rank knows the global row layout `(m, n,
# parts_rank_size, rank)` before invoking an MG solver.
#
from __future__ import annotations

import json
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple


@dataclass
class PartitionDescriptor:
    """Global row layout: which rank holds how many rows, plus (m, n)."""

    parts_rank_size: List[Tuple[int, int]]  # [(rank, rows_in_that_rank_chunk), ...]
    m: int  # total rows
    n: int  # cols
    rank: int

    @classmethod
    def build(
        cls,
        partition_rows: Sequence[int],
        total_cols: int,
        rank: int = 0,
        rendezvous=None,
    ) -> "PartitionDescriptor":
        """Build the descriptor.

        Single-controller mode passes every partition's row count directly.
        SPMD mode passes this rank's counts and a `rendezvous` whose
        ``allgather`` merges them across ranks (same shape as the reference's
        BarrierTaskContext.allGather of JSON strings, utils.py:192-210).
        """
        if rendezvous is not None:
            payload = json.dumps({"rank": rank, "rows": list(partition_rows)})
            gathered = rendezvous.allgather(payload)
            pairs: List[Tuple[int, int]] = []
            for msg in gathered:
                obj = json.loads(msg)
                pairs.extend((obj["rank"], r) for r in obj["rows"])
            pairs.sort()
        else:
            pairs = [(i, r) for i, r in enumerate(partition_rows)]
        m = sum(r for _, r in pairs)
        return cls(parts_rank_size=pairs, m=m, n=total_cols, rank=rank)

    def rows_of(self, rank: int) -> int:
        return sum(r for rk, r in self.parts_rank_size if rk == rank)

    def row_offset_of(self, rank: int) -> int:
        off = 0
        for rk, r in self.parts_rank_size:
            if rk < rank:
                off += r
        return off
