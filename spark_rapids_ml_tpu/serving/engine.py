#
# ScoringEngine: concurrent predict requests, micro-batched up the bucket
# ladder, dispatched async (docs/serving.md "Scoring engine").
#
# The latency pipeline for one request:
#
#   submit() ──queue──▶ coalesce (bounded window, same-model requests merge
#   into one block) ──▶ PredictProgram.dispatch per ≤cap chunk (pads up the
#   geometric bucket ladder; NO host fetch — the device work is in flight)
#   ──▶ response assembly: the ONE `block_until_ready` point ──▶ per-request
#   output slices ──▶ futures resolve.
#
# Because `predict` is row-parallel by contract (the bucket-padding
# invariant, core.PredictProgram), a coalesced batch's per-request slices are
# bit-identical to serving each request solo — pinned by
# tests/test_serving.py and measured live by benchmark/bench_serving.py.
#
# Telemetry (docs/observability.md "Serving plane"): serve.requests/rows/
# batches, serve.coalesced_batches/coalesced_requests, serve.bucket_hits,
# and the serve.queue_wait_s / serve.e2e_s latency histograms.
#
# The async contract is CI-enforced (ci/analysis `serve-dispatch`): no
# direct jit/block_until_ready in this package outside the waived assembly
# point below.
#
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from .. import telemetry
from ..utils import get_logger, lockcheck, numcheck
from .registry import ModelRegistry


class ScoreFuture:
    """Handle for one in-flight scoring request."""

    __slots__ = ("name", "features", "_event", "_result", "_error", "t_submit")

    def __init__(self, name: str, features: np.ndarray, t_submit: float) -> None:
        self.name = name
        self.features = features
        self._event = threading.Event()
        self._result: Any = None
        self._error: Optional[BaseException] = None
        self.t_submit = t_submit

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = 30.0) -> Any:
        """Block until the response is assembled. Returns the per-algo predict
        output for THIS request's rows (array, or tuple of arrays for
        multi-output models). Raises the scoring error if the dispatch
        failed, TimeoutError if the deadline elapses first."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"scoring request for model {self.name!r} did not complete "
                f"within {timeout}s"
            )
        if self._error is not None:
            raise self._error
        return self._result

    def _resolve(self, result: Any = None, error: Optional[BaseException] = None) -> None:
        self._result = result
        self._error = error
        self._event.set()


class ScoringEngine:
    """Resident scoring service over a `ModelRegistry` (docs/serving.md).

    One worker thread drains the request queue: the oldest request opens a
    micro-batch, same-model requests arriving within the coalesce window
    (``config["serve_coalesce_window_ms"]``) merge into it up the bucket
    ladder, and the whole block dispatches as one predict program call per
    ``config["serve_max_batch_rows"]`` chunk. Use as a context manager, or
    `start()`/`stop()` explicitly."""

    _POLL_S = 0.05  # worker wake-up bound: stop/new-work latency ceiling

    def __init__(
        self,
        registry: ModelRegistry,
        *,
        coalesce_window_s: Optional[float] = None,
        max_batch_rows: Optional[int] = None,
    ) -> None:
        from ..core import config

        self.registry = registry
        if coalesce_window_s is None:
            coalesce_window_s = float(config.get("serve_coalesce_window_ms", 2.0)) / 1e3
        self._window_s = max(0.0, float(coalesce_window_s))
        self._max_rows = int(max_batch_rows or config.get("serve_max_batch_rows", 8192))
        self._cond = lockcheck.make_condition("serving.engine.ScoringEngine._cond")
        self._queue: "deque[ScoreFuture]" = deque()
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._logger = get_logger(type(self))
        # runtime numerics sanitizer (SRML_NUMCHECK=1): resolved once per
        # engine; disabled = a None attribute, one test per dispatch group
        self._numcheck = numcheck.hook()

    # ---------------------------------------------------------- lifecycle --
    def start(self) -> "ScoringEngine":
        # opt-in live scrape surface (SRML_METRICS_PORT): a serving process
        # is exactly what /metrics + /healthz exist for
        from ..ops_plane import ensure_server

        ensure_server()
        with self._cond:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop = False
            self._thread = threading.Thread(
                target=self._loop, name="srml-scoring-engine", daemon=True
            )
            self._thread.start()
        return self

    def stop(self, timeout: float = 30.0) -> None:
        """Drain the queue, then stop the worker. Requests still queued when
        the drain deadline elapses fail with RuntimeError."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout)
        with self._cond:
            while self._queue:
                self._queue.popleft()._resolve(
                    error=RuntimeError("scoring engine stopped before dispatch")
                )
            self._thread = None

    def __enter__(self) -> "ScoringEngine":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    # ------------------------------------------------------------ requests --
    def submit(self, name: str, features: Any) -> ScoreFuture:
        """Enqueue one scoring request against resident model `name`.
        Validates residency and feature width AT SUBMIT so the caller gets
        the error synchronously, not inside a future."""
        entry = self.registry.get(name)  # KeyError for unknown/evicted models
        feats = np.asarray(features)
        if hasattr(features, "todense"):
            feats = np.asarray(features.todense())
        if feats.ndim != 2:
            raise ValueError(
                f"features must be a [rows, {entry.n_cols}] block; got shape "
                f"{feats.shape}"
            )
        if entry.n_cols and feats.shape[1] != entry.n_cols:
            raise ValueError(
                f"model {name!r} expects {entry.n_cols} features; got "
                f"{feats.shape[1]}"
            )
        fut = ScoreFuture(name, feats, time.monotonic())
        with self._cond:
            if self._stop or self._thread is None:
                raise RuntimeError("scoring engine is not running (call start())")
            self._queue.append(fut)
            self._cond.notify_all()
        return fut

    def score(self, name: str, features: Any, timeout: Optional[float] = 30.0) -> Any:
        """Blocking convenience: submit + wait for the response."""
        return self.submit(name, features).result(timeout)

    def stats(self) -> Dict[str, Any]:
        """Latency-centric view of the serve.* telemetry (p50/p99 via
        `telemetry.summarize_histogram` — the ONE shared extraction, also
        behind `FitScheduler.stats`; None while telemetry is off or nothing
        has been served)."""
        qw = telemetry.summarize_histogram("serve.queue_wait_s")
        e2e = telemetry.summarize_histogram("serve.e2e_s")
        return {
            "queue_wait_p50_s": qw["p50"],
            "queue_wait_p99_s": qw["p99"],
            "e2e_p50_s": e2e["p50"],
            "e2e_p99_s": e2e["p99"],
        }

    # -------------------------------------------------------------- worker --
    def _loop(self) -> None:
        while True:  # blocking-ok: every wait below is bounded by _POLL_S; exits when _stop is set and the queue drained
            with self._cond:
                while not self._queue and not self._stop:
                    self._cond.wait(self._POLL_S)
                if not self._queue:
                    if self._stop:
                        return
                    continue
                first = self._queue.popleft()
            group = self._coalesce(first)
            self._dispatch_group(group)

    def _coalesce(self, first: ScoreFuture) -> List[ScoreFuture]:
        """Grow a micro-batch from `first`: same-model requests already
        queued (or arriving inside the bounded coalesce window) merge until
        the batch reaches the row cap. Other models' requests stay queued
        in order for the next batch. A zero window disables coalescing
        entirely (pure latency mode, docs/serving.md) — even already-queued
        same-model requests dispatch solo."""
        if self._window_s <= 0.0:
            return [first]
        group = [first]
        rows = int(first.features.shape[0])
        deadline = time.monotonic() + self._window_s
        while rows < self._max_rows:
            with self._cond:
                took = None
                for i, fut in enumerate(self._queue):
                    if fut.name == first.name:
                        took = fut
                        del self._queue[i]
                        break
                if took is None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or self._stop:
                        break
                    self._cond.wait(min(remaining, self._POLL_S))
                    continue
            group.append(took)
            rows += int(took.features.shape[0])
        return group

    def _dispatch_group(self, group: List[ScoreFuture]) -> None:
        import jax

        from ..parallel.chaos import maybe_delay_stage
        from ..parallel.mesh import dtype_scope

        # chaos latency injection (`delay:stage=serve:seconds=`): the spike
        # the SLO burn-rate acceptance test drives through the fast window
        maybe_delay_stage("serve")
        t0 = time.monotonic()
        reg = telemetry.registry() if telemetry.enabled() else None
        if reg is not None:
            reg.inc("serve.requests", len(group))
            reg.inc("serve.batches")
            if len(group) > 1:
                reg.inc("serve.coalesced_batches")
                reg.inc("serve.coalesced_requests", len(group))
            for fut in group:
                reg.observe("serve.queue_wait_s", t0 - fut.t_submit)
        try:
            # one efficiency attribution window per dispatch group, keyed to
            # the per-model serving tenant ("serving:<name>") so the split
            # lands next to the model's HBM byte-seconds in tenant_usage()
            with telemetry.attribution(
                "serve_dispatch", tenant=f"serving:{group[0].name}"
            ):
                entry = self.registry.get(group[0].name)  # use-touch: keeps it MRU
                program = entry.program
                if program is None:
                    # evicted between get() and here (_evict_locked nulls the
                    # program — the entry object may still be in a caller's
                    # hands): fail typed like a never-resident model, not with
                    # an AttributeError off the None
                    raise KeyError(
                        f"model {group[0].name!r} was evicted mid-flight"
                    )
                sizes = [int(f.features.shape[0]) for f in group]
                block = (
                    np.concatenate([f.features for f in group], axis=0)
                    if len(group) > 1
                    else group[0].features
                )
                n = int(block.shape[0])
                model = entry.model
                if reg is not None and n:
                    # per-bucket roofline numerator (the `_serve_flop_estimate`
                    # hook): feeds `efficiency.serve_mfu` when a peak is set
                    fhook = getattr(model, "_serve_flop_estimate", None)
                    if fhook is not None:
                        try:
                            flops = fhook(n, int(block.shape[1]))
                        except Exception:
                            flops = None
                        if flops:
                            telemetry.note_flops(
                                float(flops), chips=program.multiple
                            )
                with dtype_scope(
                    np.float32 if model._float32_inputs else np.float64,
                    model._matmul_precision,
                ):
                    in_flight = []
                    # chunk oversized blocks at the program's ladder cap; a
                    # zero-row block still dispatches once (shaped empty outputs)
                    for start in range(0, n, program.cap) if n else (0,):
                        chunk = block[start : min(start + program.cap, n)]
                        in_flight.append(program.dispatch(chunk))
                        if reg is not None and not program.last_dispatch_new_shape:
                            reg.inc("serve.bucket_hits")
                    # ---- response assembly: THE one blocking point -----------
                    with telemetry.device_wait("serve_assembly"):
                        jax.block_until_ready([r for r, _ in in_flight])  # serve-ok: the engine's single response-assembly sync point (docs/serving.md async contract)
                    outs = [program.fetch(r, nv) for r, nv in in_flight]
                if self._numcheck is not None:
                    # response assembly is the serving plane's one host boundary:
                    # the fetched outputs are swept before any tenant sees them.
                    # allow_inf: top-k pads short result rows with inf distances
                    for oi, out in enumerate(outs):
                        vals = out if isinstance(out, tuple) else (out,)
                        self._numcheck(
                            "serving.response", solver=group[0].name, allow_inf=True,
                            **{f"chunk{oi}_out{j}": v for j, v in enumerate(vals)},
                        )
                with telemetry.host_section("serve_response"):
                    self._resolve_group(group, sizes, outs)
                if reg is not None:
                    reg.inc("serve.rows", n)
                    t1 = time.monotonic()
                    for fut in group:
                        reg.observe("serve.e2e_s", t1 - fut.t_submit)
        except Exception as e:
            if reg is not None:
                # the error-rate SLO's numerator, one per failed request
                reg.inc("serve.errors", len(group))
            self._logger.warning(
                "scoring dispatch for model %r failed: %s: %s",
                group[0].name, type(e).__name__, e,
            )
            for fut in group:
                fut._resolve(error=e)
        # latency histograms were just recorded: the SLO monitors' inline
        # evaluation point (throttled to one bucket width; no-op w/o specs)
        from ..ops_plane import slo as _slo

        _slo.maybe_evaluate()

    @staticmethod
    def _resolve_group(
        group: List[ScoreFuture], sizes: List[int], outs: List[Any]
    ) -> None:
        """Concatenate the per-chunk outputs and slice each request's rows
        back out, preserving the per-algo output structure (array or tuple)."""
        if isinstance(outs[0], tuple):
            merged: Any = tuple(
                np.concatenate(parts, axis=0) for parts in zip(*outs)
            )
        else:
            merged = np.concatenate(outs, axis=0)
        offset = 0
        for fut, rows in zip(group, sizes):
            if isinstance(merged, tuple):
                fut._resolve(tuple(m[offset : offset + rows] for m in merged))
            else:
                fut._resolve(merged[offset : offset + rows])
            offset += rows
