#
# ScoringEngine: concurrent predict requests, micro-batched up the bucket
# ladder, dispatched async (docs/serving.md "Scoring engine").
#
# The latency pipeline for one request:
#
#   submit() ──queue──▶ coalesce (bounded window, same-model requests merge
#   into one block) ──▶ PredictProgram.dispatch per ≤cap chunk (pads up the
#   geometric bucket ladder; NO host fetch — the device work is in flight)
#   ──▶ response assembly: the ONE `block_until_ready` point ──▶ per-request
#   output slices ──▶ futures resolve.
#
# Because `predict` is row-parallel by contract (the bucket-padding
# invariant, core.PredictProgram), a coalesced batch's per-request slices are
# bit-identical to serving each request solo — pinned by
# tests/test_serving.py and measured live by benchmark/bench_serving.py.
#
# Telemetry (docs/observability.md "Serving plane"): serve.requests/rows/
# batches, serve.coalesced_batches/coalesced_requests, serve.bucket_hits,
# and the serve.queue_wait_s / serve.e2e_s latency histograms (plus their
# per-tenant siblings via `telemetry.tenant_metric`).
#
# Overload control (docs/serving.md "Overload & backpressure"): every
# request carries a server-side monotonic deadline (submit(deadline_ms=),
# default `config["serve_default_deadline_ms"]`) — expired requests NEVER
# dispatch (typed RequestTimeoutError) — and admission is the closed loop's
# refusal point: the bounded queue, the deadline-feasibility check against
# the live queue-wait p99, and the per-tenant backpressure ladder all live
# in `serving.overload.OverloadController` and raise typed
# ServeOverloadError BEFORE the request queues.
#
# The async contract is CI-enforced (ci/analysis `serve-dispatch`): no
# direct jit/block_until_ready in this package outside the waived assembly
# point below.
#
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

import numpy as np

from .. import telemetry
from ..errors import RequestTimeoutError, ServingStoppedError
from ..utils import get_logger, lockcheck, numcheck
from .overload import OverloadController, plan_target_rows, plan_window
from .registry import ModelRegistry


class ScoreFuture:
    """Handle for one in-flight scoring request."""

    __slots__ = (
        "name", "features", "_event", "_result", "_error", "t_submit",
        "t_done", "rows", "tenant", "deadline", "degraded",
    )

    def __init__(
        self,
        name: str,
        features: np.ndarray,
        t_submit: float,
        *,
        tenant: str = "default",
        deadline: Optional[float] = None,
        degraded: bool = False,
    ) -> None:
        self.name = name
        self.features = features
        self._event = threading.Event()
        self._result: Any = None
        self._error: Optional[BaseException] = None
        self.t_submit = t_submit
        self.t_done: Optional[float] = None  # set at resolution
        self.rows = int(features.shape[0])
        self.tenant = tenant
        # server-side deadline, ABSOLUTE monotonic seconds (None = no
        # deadline): the engine refuses to dispatch past it
        self.deadline = deadline
        # the backpressure ladder routed this request to the degraded
        # (serve_degraded_dtype) resident program
        self.degraded = degraded

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = 30.0) -> Any:
        """Block until the response is assembled. Returns the per-algo predict
        output for THIS request's rows (array, or tuple of arrays for
        multi-output models). Raises the scoring error if the dispatch
        failed, bare TimeoutError if `timeout` elapses first.

        A client timeout here does NOT cancel the request: it stays queued
        (or in flight) server-side and still resolves this future when it
        completes — only the SERVER-side deadline (``submit(deadline_ms=)``,
        default ``config["serve_default_deadline_ms"]``) stops undispatched
        work, failing the future with the typed `RequestTimeoutError`
        instead. A caller that gives up should therefore pass a matching
        ``deadline_ms`` at submit so its abandoned request cannot burn
        device time (docs/serving.md "Overload & backpressure")."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"scoring request for model {self.name!r} did not complete "
                f"within {timeout}s"
            )
        if self._error is not None:
            raise self._error
        return self._result

    def _resolve(self, result: Any = None, error: Optional[BaseException] = None) -> None:
        self._result = result
        self._error = error
        # monotonic resolution time: harnesses reading a future AFTER the
        # fact (the saturation lane's drain) still see the true e2e
        self.t_done = time.monotonic()
        self._event.set()


class ScoringEngine:
    """Resident scoring service over a `ModelRegistry` (docs/serving.md).

    One worker thread drains the request queue: the oldest request opens a
    micro-batch, same-model requests arriving within the coalesce window
    (``config["serve_coalesce_window_ms"]``) merge into it up the bucket
    ladder, and the whole block dispatches as one predict program call per
    ``config["serve_max_batch_rows"]`` chunk. Use as a context manager, or
    `start()`/`stop()` explicitly."""

    _POLL_S = 0.05  # worker wake-up bound: stop/new-work latency ceiling

    def __init__(
        self,
        registry: ModelRegistry,
        *,
        coalesce_window_s: Optional[float] = None,
        max_batch_rows: Optional[int] = None,
    ) -> None:
        from ..core import config

        self.registry = registry
        # an EXPLICIT constructor window is a static override: the adaptive
        # planner never touches it (docs/serving.md "Adaptive batching")
        self._window_overridden = coalesce_window_s is not None
        if coalesce_window_s is None:
            coalesce_window_s = float(config.get("serve_coalesce_window_ms", 2.0)) / 1e3
        self._window_s = max(0.0, float(coalesce_window_s))
        self._max_rows = int(max_batch_rows or config.get("serve_max_batch_rows", 8192))
        self._cond = lockcheck.make_condition("serving.engine.ScoringEngine._cond")
        self._queue: "deque[ScoreFuture]" = deque()
        self._queued_rows = 0  # guarded-by: _cond
        self._thread: Optional[threading.Thread] = None
        self._stop = False
        self._logger = get_logger(type(self))
        # deadline admission + the per-tenant backpressure ladder
        self._overload = OverloadController()
        # runtime numerics sanitizer (SRML_NUMCHECK=1): resolved once per
        # engine; disabled = a None attribute, one test per dispatch group
        self._numcheck = numcheck.hook()

    # ---------------------------------------------------------- lifecycle --
    def start(self) -> "ScoringEngine":
        # opt-in live scrape surface (SRML_METRICS_PORT): a serving process
        # is exactly what /metrics + /healthz exist for
        from ..ops_plane import ensure_server

        ensure_server()
        with self._cond:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop = False
            self._thread = threading.Thread(
                target=self._loop, name="srml-scoring-engine", daemon=True
            )
            self._thread.start()
        return self

    def stop(self, timeout: float = 30.0) -> None:
        """Drain the queue, then stop the worker. Requests still queued when
        the drain deadline elapses fail with the typed `ServingStoppedError`
        (carrying the model name and the request's queue position at
        shutdown), so callers can tell "service went away" from a scoring
        failure."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout)
        with self._cond:
            position = 0
            while self._queue:
                fut = self._queue.popleft()
                self._queued_rows -= fut.rows
                fut._resolve(
                    error=ServingStoppedError(fut.name, queue_position=position)
                )
                position += 1
            self._queued_rows = 0
            self._thread = None

    def __enter__(self) -> "ScoringEngine":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    # ------------------------------------------------------------ requests --
    def submit(
        self,
        name: str,
        features: Any,
        *,
        deadline_ms: Optional[float] = None,
        tenant: str = "default",
    ) -> ScoreFuture:
        """Enqueue one scoring request against resident model `name`.
        Validates residency and feature width AT SUBMIT so the caller gets
        the error synchronously, not inside a future.

        `deadline_ms` is the SERVER-side deadline (monotonic clock, default
        ``config["serve_default_deadline_ms"]``; <= 0 disables): the engine
        never dispatches an expired request (typed `RequestTimeoutError` on
        the future), and admission refuses synchronously — typed
        `ServeOverloadError` — when the bounded queue is full, the live
        queue-wait p99 predicts the deadline cannot be met, or `tenant`'s
        backpressure ladder is throttling/shedding (docs/serving.md
        "Overload & backpressure")."""
        from ..core import config

        entry = self.registry.get(name)  # KeyError for unknown/evicted models
        feats = np.asarray(features)
        if hasattr(features, "todense"):
            feats = np.asarray(features.todense())
        if feats.ndim != 2:
            raise ValueError(
                f"features must be a [rows, {entry.n_cols}] block; got shape "
                f"{feats.shape}"
            )
        if entry.n_cols and feats.shape[1] != entry.n_cols:
            raise ValueError(
                f"model {name!r} expects {entry.n_cols} features; got "
                f"{feats.shape[1]}"
            )
        now = time.monotonic()
        if deadline_ms is None:
            deadline_ms = float(config.get("serve_default_deadline_ms", 30000.0))
        deadline = now + deadline_ms / 1e3 if deadline_ms > 0 else None
        # the ladder must ALSO advance on the admission path: a fully-shed
        # tenant generates no dispatches, so without this hook its burn
        # would never be re-read and a shed would be permanent (throttled
        # to one pass per metrics bucket, same as the dispatch-path hook)
        self._overload.maybe_evaluate(now)
        # admission: the typed refusal point (queue bound, deadline
        # feasibility, the tenant's ladder) — BEFORE anything queues. The
        # depth/rows snapshot is taken under the lock, then admission runs
        # outside it (admit touches the controller's own lock and telemetry).
        with self._cond:
            q_depth, q_rows = len(self._queue), self._queued_rows
        degraded = self._overload.admit(
            model=name, tenant=tenant, rows=int(feats.shape[0]),
            deadline_s=deadline, now=now,
            queue_depth=q_depth, queue_rows=q_rows,
        )
        fut = ScoreFuture(
            name, feats, now, tenant=tenant, deadline=deadline,
            degraded=degraded and entry.degraded_program is not None,
        )
        with self._cond:
            if self._stop or self._thread is None:
                raise RuntimeError("scoring engine is not running (call start())")
            self._queue.append(fut)
            self._queued_rows += fut.rows
            if telemetry.enabled():
                reg = telemetry.registry()
                reg.gauge("serve.queue_depth", float(len(self._queue)))
                reg.gauge("serve.queue_rows", float(self._queued_rows))
            self._cond.notify_all()
        return fut

    def score(self, name: str, features: Any, timeout: Optional[float] = 30.0) -> Any:
        """Blocking convenience: submit + wait for the response."""
        return self.submit(name, features).result(timeout)

    def stats(self) -> Dict[str, Any]:
        """Latency-centric view of the serve.* telemetry (p50/p99 via
        `telemetry.summarize_histogram` — the ONE shared extraction, also
        behind `FitScheduler.stats`; None while telemetry is off or nothing
        has been served), plus the live queue depth, the overload counters,
        and the per-tenant view: each tenant's queue-wait/e2e p50/p99 (the
        `telemetry.tenant_metric` histogram siblings) and its backpressure
        ladder state."""
        qw = telemetry.summarize_histogram("serve.queue_wait_s")
        e2e = telemetry.summarize_histogram("serve.e2e_s")
        counters: Dict[str, float] = {}
        if telemetry.enabled():
            counters = telemetry.registry().snapshot()["counters"]
        with self._cond:
            q_depth, q_rows = len(self._queue), self._queued_rows
        tenants: Dict[str, Any] = {}
        for tenant, view in self._overload.stats().items():
            tqw = telemetry.summarize_histogram(
                telemetry.tenant_metric("serve.queue_wait_s", tenant)
            )
            te2e = telemetry.summarize_histogram(
                telemetry.tenant_metric("serve.e2e_s", tenant)
            )
            tenants[tenant] = {
                **view,
                "queue_wait_p50_s": tqw["p50"],
                "queue_wait_p99_s": tqw["p99"],
                "e2e_p50_s": te2e["p50"],
                "e2e_p99_s": te2e["p99"],
            }
        return {
            "queue_wait_p50_s": qw["p50"],
            "queue_wait_p99_s": qw["p99"],
            "e2e_p50_s": e2e["p50"],
            "e2e_p99_s": e2e["p99"],
            "queue_depth": q_depth,
            "queue_rows": q_rows,
            "expired_requests": int(counters.get("serve.expired_requests", 0)),
            "rejected_requests": int(counters.get("serve.rejected_requests", 0)),
            "shed_requests": int(counters.get("serve.shed_requests", 0)),
            "throttled_requests": int(counters.get("serve.throttled_requests", 0)),
            "degraded_requests": int(counters.get("serve.degraded_requests", 0)),
            "tenants": tenants,
        }

    # -------------------------------------------------------------- worker --
    def _loop(self) -> None:
        while True:  # blocking-ok: every wait below is bounded by _POLL_S; exits when _stop is set and the queue drained
            with self._cond:
                while not self._queue and not self._stop:
                    self._cond.wait(self._POLL_S)
                if not self._queue:
                    if self._stop:
                        return
                    continue
                first = self._queue.popleft()
                self._queued_rows -= first.rows
            # the deadline contract: an expired request NEVER dispatches —
            # it fails fast here (typed), before any coalescing or device work
            if first.deadline is not None and time.monotonic() > first.deadline:
                self._expire(first)
                continue
            group = self._coalesce(first)
            self._dispatch_group(group)

    def _expire(self, fut: ScoreFuture) -> None:
        """Fail one expired request with the typed `RequestTimeoutError`
        (counter: serve.expired_requests). The request never touched the
        device — this IS the fail-fast path."""
        now = time.monotonic()
        if telemetry.enabled():
            telemetry.registry().inc("serve.expired_requests")
        with self._cond:
            q_depth, q_rows = len(self._queue), self._queued_rows
        fut._resolve(
            error=RequestTimeoutError(
                f"scoring request for model {fut.name!r} expired before "
                "dispatch",
                model=fut.name,
                deadline_ms=(fut.deadline - fut.t_submit) * 1e3,
                waited_ms=(now - fut.t_submit) * 1e3,
                queue_depth=q_depth,
                queue_rows=q_rows,
            )
        )

    def _plan_batch(self) -> tuple:
        """The micro-batch plan for the NEXT coalesce: (window_s,
        target_rows). Static (`serve_adaptive_batching` off, or an explicit
        constructor window) returns the configured window and the row cap;
        adaptive delegates to the pure planners in `serving.overload`,
        feeding them the windowed arrival rate and queue-wait p99 —
        uncongested traffic still gets EXACTLY the static values."""
        from ..core import config

        base = self._window_s
        if (
            self._window_overridden
            or not bool(config.get("serve_adaptive_batching", True))
            or not telemetry.enabled()
        ):
            return base, self._max_rows
        reg = telemetry.registry()
        fast_w = reg.bucket_seconds() * 3.0
        rate = reg.rate("serve.rows", fast_w)
        p99 = reg.window_quantile("serve.queue_wait_s", 0.99, fast_w)
        with self._cond:
            q_rows = self._queued_rows
        window_s = plan_window(
            base,
            floor_s=float(config.get("serve_coalesce_window_floor_ms", 0.5)) / 1e3,
            ceiling_s=float(config.get("serve_coalesce_window_ceiling_ms", 20.0)) / 1e3,
            arrival_rows_per_s=rate,
            queue_rows=q_rows,
            queue_wait_p99_s=p99,
            max_rows=self._max_rows,
        )
        target_rows = plan_target_rows(
            min_rows=int(config.get("transform_bucket_min_rows", 8)),
            max_rows=self._max_rows,
            queue_rows=q_rows,
            arrival_rows_per_s=rate,
            window_s=window_s,
            congested=bool(p99 is not None and base > 0.0 and p99 > base),
        )
        reg.gauge("serve.adaptive_window_ms", window_s * 1e3)
        return window_s, target_rows

    def _coalesce(self, first: ScoreFuture) -> List[ScoreFuture]:
        """Grow a micro-batch from `first`: same-model (and same
        degraded-rung) requests already queued (or arriving inside the
        coalesce window) merge until the batch reaches the row target.
        Other models' requests stay queued in order for the next batch. A
        zero window disables coalescing entirely (pure latency mode,
        docs/serving.md) — even already-queued same-model requests dispatch
        solo. The window and target come from `_plan_batch` (adaptive under
        congestion, static otherwise)."""
        window_s, target_rows = self._plan_batch()
        if window_s <= 0.0:
            return [first]
        group = [first]
        rows = int(first.features.shape[0])
        deadline = time.monotonic() + window_s
        while rows < target_rows:
            with self._cond:
                took = None
                for i, fut in enumerate(self._queue):
                    if fut.name == first.name and fut.degraded == first.degraded:
                        took = fut
                        del self._queue[i]
                        self._queued_rows -= fut.rows
                        break
                if took is None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or self._stop:
                        break
                    self._cond.wait(min(remaining, self._POLL_S))
                    continue
            group.append(took)
            rows += int(took.features.shape[0])
        return group

    def _dispatch_group(self, group: List[ScoreFuture]) -> None:
        import jax

        from ..parallel.chaos import maybe_delay_stage
        from ..parallel.mesh import dtype_scope

        # chaos latency injection (`delay:stage=serve:seconds=`): the spike
        # the SLO burn-rate acceptance test drives through the fast window
        maybe_delay_stage("serve")
        t0 = time.monotonic()
        reg = telemetry.registry() if telemetry.enabled() else None
        # members whose deadline passed while the batch formed (or during an
        # injected delay) fail typed HERE, before any device work — the
        # zero-over-deadline-dispatches invariant the saturation lane gates
        live: List[ScoreFuture] = []
        for fut in group:
            if fut.deadline is not None and t0 > fut.deadline:
                self._expire(fut)
            else:
                live.append(fut)
        group = live
        if not group:
            return
        if reg is not None:
            # tripwire, expected to stay 0 forever: a request past its
            # deadline reaching THIS point means the filter above regressed.
            # Measured at t0 — the same instant the filter decided at — so a
            # deadline expiring DURING this bookkeeping can't false-trip it
            late = sum(
                1 for f in group
                if f.deadline is not None and t0 > f.deadline
            )
            if late:
                reg.inc("serve.overdeadline_dispatches", late)
            reg.inc("serve.requests", len(group))
            reg.inc("serve.batches")
            if len(group) > 1:
                reg.inc("serve.coalesced_batches")
                reg.inc("serve.coalesced_requests", len(group))
            if group[0].degraded:
                reg.inc("serve.degraded_requests", len(group))
                reg.inc(
                    "serve.degraded_rows", sum(f.rows for f in group)
                )
            for fut in group:
                wait = t0 - fut.t_submit
                reg.observe("serve.queue_wait_s", wait)
                reg.observe(
                    telemetry.tenant_metric("serve.queue_wait_s", fut.tenant),
                    wait,
                )
        try:
            # one efficiency attribution window per dispatch group, keyed to
            # the per-model serving tenant ("serving:<name>") so the split
            # lands next to the model's HBM byte-seconds in tenant_usage()
            with telemetry.attribution(
                "serve_dispatch", tenant=f"serving:{group[0].name}"
            ):
                entry = self.registry.get(group[0].name)  # use-touch: keeps it MRU
                # the degrade rung: the ladder routed this group to the
                # registry's serve_degraded_dtype sibling program; a rung
                # evicted mid-flight falls back to the primary (degrade is
                # an optimization, never a failure)
                program = (
                    entry.degraded_program
                    if group[0].degraded and entry.degraded_program is not None
                    else entry.program
                )
                if program is None:
                    # evicted between get() and here (_evict_locked nulls the
                    # program — the entry object may still be in a caller's
                    # hands): fail typed like a never-resident model, not with
                    # an AttributeError off the None
                    raise KeyError(
                        f"model {group[0].name!r} was evicted mid-flight"
                    )
                sizes = [int(f.features.shape[0]) for f in group]
                block = (
                    np.concatenate([f.features for f in group], axis=0)
                    if len(group) > 1
                    else group[0].features
                )
                n = int(block.shape[0])
                model = entry.model
                if reg is not None and n:
                    # per-bucket roofline numerator (the `_serve_flop_estimate`
                    # hook): feeds `efficiency.serve_mfu` when a peak is set
                    fhook = getattr(model, "_serve_flop_estimate", None)
                    if fhook is not None:
                        try:
                            flops = fhook(n, int(block.shape[1]))
                        except Exception:
                            flops = None
                        if flops:
                            telemetry.note_flops(
                                float(flops), chips=program.multiple
                            )
                with dtype_scope(
                    np.float32 if model._float32_inputs else np.float64,
                    model._matmul_precision,
                ):
                    in_flight = []
                    # chunk oversized blocks at the program's ladder cap; a
                    # zero-row block still dispatches once (shaped empty outputs)
                    for start in range(0, n, program.cap) if n else (0,):
                        chunk = block[start : min(start + program.cap, n)]
                        in_flight.append(program.dispatch(chunk))
                        if reg is not None and not program.last_dispatch_new_shape:
                            reg.inc("serve.bucket_hits")
                    # ---- response assembly: THE one blocking point -----------
                    with telemetry.device_wait("serve_assembly"):
                        jax.block_until_ready([r for r, _ in in_flight])  # serve-ok: the engine's single response-assembly sync point (docs/serving.md async contract)
                    outs = [program.fetch(r, nv) for r, nv in in_flight]
                if self._numcheck is not None:
                    # response assembly is the serving plane's one host boundary:
                    # the fetched outputs are swept before any tenant sees them.
                    # allow_inf: top-k pads short result rows with inf distances
                    for oi, out in enumerate(outs):
                        vals = out if isinstance(out, tuple) else (out,)
                        self._numcheck(
                            "serving.response", solver=group[0].name, allow_inf=True,
                            **{f"chunk{oi}_out{j}": v for j, v in enumerate(vals)},
                        )
                with telemetry.host_section("serve_response"):
                    self._resolve_group(group, sizes, outs)
                if reg is not None:
                    reg.inc("serve.rows", n)
                    t1 = time.monotonic()
                    tenant_rows: Dict[str, int] = {}
                    for fut in group:
                        e2e = t1 - fut.t_submit
                        reg.observe("serve.e2e_s", e2e)
                        reg.observe(
                            telemetry.tenant_metric("serve.e2e_s", fut.tenant),
                            e2e,
                        )
                        tenant_rows[fut.tenant] = (
                            tenant_rows.get(fut.tenant, 0) + fut.rows
                        )
                    for tenant, t_rows in tenant_rows.items():
                        reg.inc(
                            telemetry.tenant_metric("serve.rows", tenant), t_rows
                        )
        except Exception as e:
            if reg is not None:
                # the error-rate SLO's numerator, one per failed request
                reg.inc("serve.errors", len(group))
            self._logger.warning(
                "scoring dispatch for model %r failed: %s: %s",
                group[0].name, type(e).__name__, e,
            )
            for fut in group:
                fut._resolve(error=e)
        # latency histograms were just recorded: the SLO monitors' inline
        # evaluation point (throttled to one bucket width; no-op w/o specs)
        from ..ops_plane import slo as _slo

        _slo.maybe_evaluate()
        # ... and the backpressure ladder's, reading those verdicts plus the
        # per-tenant burns (same throttling; inert without a serving spec)
        self._overload.maybe_evaluate()

    @staticmethod
    def _resolve_group(
        group: List[ScoreFuture], sizes: List[int], outs: List[Any]
    ) -> None:
        """Concatenate the per-chunk outputs and slice each request's rows
        back out, preserving the per-algo output structure (array or tuple)."""
        if isinstance(outs[0], tuple):
            merged: Any = tuple(
                np.concatenate(parts, axis=0) for parts in zip(*outs)
            )
        else:
            merged = np.concatenate(outs, axis=0)
        offset = 0
        for fut, rows in zip(group, sizes):
            if isinstance(merged, tuple):
                fut._resolve(tuple(m[offset : offset + rows] for m in merged))
            else:
                fut._resolve(merged[offset : offset + rows])
            offset += rows
