#
# ModelRegistry: many fitted models resident in HBM under admission control
# (docs/serving.md "Registry lifecycle").
#
# A load is a three-step transaction, all under the registry lock:
#
#   1. ADMISSION — `memory.admit_model_load` charges the model's placement
#      terms plus a per-bucket predict workspace term against the per-device
#      budget MINUS what the shared `scheduler.HbmLedger` already holds —
#      resident models (each keeps a ledger reservation from admission until
#      eviction) AND concurrently running/scheduled fits (docs/scheduling.md
#      "The shared ledger"). Over budget: evict the
#      least-recently-USED resident (scoring touches move entries to MRU) and
#      retry; nothing left to evict: the typed `HbmBudgetError` propagates,
#      and the refusal — naming its largest byte term — is stamped on
#      `model._serve_metrics["admission"]`, mirroring the fit-side
#      `_fit_metrics["admission"]` stamp.
#   2. PLACEMENT — the model's serving hook (`_serve_program`) constructs the
#      resident `PredictProgram` (device state placed once, held for the
#      entry's lifetime).
#   3. PREWARM — every bucket-ladder rung up to
#      `config["serve_prewarm_rows"]` is compiled through the persistent
#      compile cache (`PredictProgram.prewarm`), so the model's first query
#      pays dispatch, never compile.
#
# Eviction (explicit `evict()`, pressure during a later load, or a reload of
# the same name) drops the entry's program/state references — the only HBM
# pins — and re-stamps the evicted model's `_serve_metrics["admission"]`
# with verdict "evicted" so the model itself records why it left.
#
from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .. import telemetry
from ..errors import HbmBudgetError
from ..utils import get_logger, lockcheck


@dataclass
class ResidentModel:
    """One registry entry: the model, its resident PredictProgram, and the
    admission verdict that let it in."""

    name: str
    model: Any
    program: Any  # core.PredictProgram (or a duck-typed per-estimator handle)
    admission: Any  # memory.AdmissionDecision
    serve_dtype: Optional[str] = None
    n_cols: int = 0
    prewarmed_rungs: int = 0
    stats: Dict[str, float] = field(default_factory=dict)
    # the opt-in degraded serving rung (config["serve_degraded_dtype"], e.g.
    # "bf16"): a SECOND resident program the backpressure ladder routes a
    # burning tenant's traffic to before shedding — its bytes honestly
    # admitted (degraded_admission) and released with the entry
    degraded_program: Any = None
    degraded_admission: Any = None
    degraded_dtype: Optional[str] = None

    @property
    def resident_bytes(self) -> int:
        total = int(self.admission.estimate.total())
        if self.degraded_admission is not None:
            total += int(self.degraded_admission.estimate.total())
        return total


class ModelRegistry:
    """Resident multi-model store for the serving plane (docs/serving.md).

    Thread-safe; `get()` is a use-touch (moves the entry to MRU), so pressure
    eviction during a load removes the model that has served least recently.
    """

    def __init__(
        self, *, prewarm: bool = True, max_batch_rows: Optional[int] = None
    ) -> None:
        from ..core import config

        self._lock = lockcheck.make_lock("serving.registry.ModelRegistry._lock", "rlock")
        self._entries: "OrderedDict[str, ResidentModel]" = OrderedDict()  # guarded-by: _lock
        self._prewarm_default = bool(prewarm)
        self._cap = int(max_batch_rows or config.get("serve_max_batch_rows", 8192))
        self._logger = get_logger(type(self))

    # ------------------------------------------------------------- reads --
    def names(self) -> List[str]:
        with self._lock:
            return list(self._entries)

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._entries

    def resident_bytes(self) -> int:
        """Admitted per-device bytes currently held by resident models —
        what the next load's admission is charged against."""
        with self._lock:
            return sum(e.resident_bytes for e in self._entries.values())

    def get(self, name: str) -> ResidentModel:
        """The resident entry for `name` (KeyError when absent/evicted).
        A USE-touch: moves the entry to most-recently-used, so serving
        traffic keeps hot models resident under eviction pressure."""
        with self._lock:
            entry = self._entries.get(name)
            if entry is None:
                raise KeyError(
                    f"model {name!r} is not resident (never loaded, or evicted)"
                )
            self._entries.move_to_end(name)
            return entry

    # ------------------------------------------------------------- loads --
    def load(
        self,
        name: str,
        model: Any,
        *,
        serve_dtype: Optional[str] = None,
        prewarm: Optional[bool] = None,
    ) -> ResidentModel:
        """Load a fitted model as `name` (see the module docstring for the
        admission → placement → prewarm transaction). Reloading an existing
        name evicts the previous entry first. Raises the typed
        `HbmBudgetError` when the model cannot fit even with every other
        resident evicted.

        Locking: serveability is PREFLIGHTED (`model._serve_check`) before
        anything is evicted — a load that can never succeed must not drop
        residents as a side effect — and placement + prewarm run OUTSIDE the
        registry lock (prewarm is tens of seconds of compile on a cold TPU
        cache; holding the lock would stall every concurrent `get()` and
        with it all scoring). The admitted bytes are reserved while the
        build runs, so concurrent loads cannot jointly overshoot the
        budget — via the shared ledger: each admission reserves there at
        admission time and keeps the claim through residency, so in-flight
        builds and residents alike are visible to every other admission in
        the process (fit-side included)."""
        from .. import memory
        from ..parallel.mesh import (
            default_local_device,
            dtype_scope,
            ensure_compilation_cache,
        )

        ensure_compilation_cache()  # prewarmed rungs should come off disk
        do_prewarm = self._prewarm_default if prewarm is None else bool(prewarm)
        # cheap preflight OUTSIDE any eviction: raises exactly what
        # _serve_program would (no hook / bad serve_dtype / unbound items)
        model._serve_check(serve_dtype)
        with self._lock:
            if name in self._entries:
                self._evict_locked(name, reason="reloaded")
            devices = [default_local_device()]
            while True:  # blocking-ok: each pass either admits or evicts one LRU entry; an empty registry re-raises — no waiting
                try:
                    # residents already hold shared-ledger reservations, so
                    # resident_bytes=0 — double-charging them here would
                    # halve the effective serving budget
                    adm = memory.admit_model_load(  # ledger-ok: THE serve-side admission entry — reserves through the shared ledger
                        model,
                        resident_bytes=0,
                        bucket_rows_count=self._cap,
                        devices=devices,
                        tenant=f"serving:{name}",
                    )
                    break
                except HbmBudgetError as e:
                    victim = next(iter(self._entries), None)
                    if victim is None:
                        # refused with nothing left to evict: stamp the
                        # refusal (largest term and all) on the model so the
                        # failure is carried, not just raised
                        model._serve_metrics["admission"] = {
                            "verdict": "refused",
                            "reason": str(e),
                            "estimate_bytes": e.estimate_bytes,
                            "capacity_bytes": e.capacity_bytes,
                            "largest_term": e.largest_term,
                            "largest_term_bytes": e.largest_term_bytes,
                        }
                        raise
                    self._logger.warning(
                        "serving budget pressure loading %r: evicting LRU "
                        "resident %r (%s)", name, victim, e,
                    )
                    self._evict_locked(victim, reason=f"pressure from load of {name!r}")
        # ---- the opt-in degraded rung: a SECOND admission, no eviction ----
        # pressure (the rung is an optimization — refusing the PRIMARY load
        # because the degrade copy doesn't fit would be backwards); a
        # refusal just means the ladder skips degrade -> shed for this model
        from ..core import config

        degraded_dtype = config.get("serve_degraded_dtype")
        degraded_adm = None
        if (
            degraded_dtype is not None
            and degraded_dtype != serve_dtype
            and degraded_dtype in getattr(model, "_serve_dtypes", ())
        ):
            try:
                degraded_adm = memory.admit_model_load(  # ledger-ok: the degrade rung's honest byte claim, released with the entry
                    model,
                    resident_bytes=0,
                    bucket_rows_count=self._cap,
                    devices=devices,
                    tenant=f"serving:{name}",
                )
            except HbmBudgetError as e:
                self._logger.warning(
                    "degraded rung (%s) for %r refused admission, serving "
                    "without it: %s", degraded_dtype, name, e,
                )
                degraded_dtype = None
        elif degraded_dtype is not None:
            degraded_dtype = None  # model can't serve it / primary already is
        # ---- placement + prewarm: NO registry lock held ------------------
        # the admission's ledger reservation is already live, so concurrent
        # loads (and fit admissions) see this build's bytes; a failed build
        # must hand them back
        try:
            dtype = "float64" if not model._float32_inputs else "float32"
            with telemetry.span(
                "serve_load", model=type(model).__name__, entry=name
            ):
                with dtype_scope(dtype, model._matmul_precision):
                    program = model._serve_program(serve_dtype, cap=self._cap)
                    n_cols = model._serve_n_cols()
                    rungs = 0
                    degraded_program = None
                    if do_prewarm:
                        max_rows = int(config.get("serve_prewarm_rows", 4096))
                        if max_rows > 0:
                            rungs = program.prewarm(n_cols, max_rows=max_rows)
                    if degraded_adm is not None:
                        degraded_program = model._serve_program(
                            degraded_dtype, cap=self._cap
                        )
                        if do_prewarm:
                            max_rows = int(config.get("serve_prewarm_rows", 4096))
                            if max_rows > 0:
                                # the rung prewarns AT LOAD like the primary:
                                # compiling mid-overload would spend seconds
                                # exactly when the ladder needs it
                                degraded_program.prewarm(n_cols, max_rows=max_rows)
        except BaseException:
            memory.release_admission(adm)
            if degraded_adm is not None:
                memory.release_admission(degraded_adm)
            raise
        with self._lock:
            if name in self._entries:  # a concurrent load published first
                self._evict_locked(name, reason="reloaded")
            entry = ResidentModel(
                name=name,
                model=model,
                program=program,
                admission=adm,
                serve_dtype=serve_dtype,
                n_cols=n_cols,
                prewarmed_rungs=rungs,
                degraded_program=degraded_program,
                degraded_admission=degraded_adm,
                degraded_dtype=degraded_dtype if degraded_adm is not None else None,
            )
            self._entries[name] = entry
            model._serve_metrics["admission"] = adm.stamp()
            if telemetry.enabled():
                reg = telemetry.registry()
                reg.inc("serve.models_loaded")
                reg.inc("serve.prewarmed_programs", rungs)
                reg.gauge("serve.resident_bytes", self.resident_bytes())
                reg.gauge("serve.resident_models", len(self._entries))
            return entry

    # --------------------------------------------------------- evictions --
    def evict(self, name: str) -> None:
        """Explicitly drop a resident model (KeyError when absent)."""
        with self._lock:
            if name not in self._entries:
                raise KeyError(f"model {name!r} is not resident")
            self._evict_locked(name, reason="explicit evict")

    def clear(self) -> None:
        """Drop every resident model (registry shutdown)."""
        with self._lock:
            for name in list(self._entries):
                self._evict_locked(name, reason="registry cleared")

    def _evict_locked(self, name: str, reason: str) -> None:
        from .. import memory
        from ..ops_plane import audit as _audit

        entry = self._entries.pop(name)
        # the model carries WHY it left residency, largest byte term and all
        # — mirroring a refused load's stamp
        stamp = dict(entry.admission.stamp())
        stamp["verdict"] = "evicted"
        stamp["reason"] = reason
        entry.model._serve_metrics["admission"] = stamp
        # the queryable side of the stamp (ops_plane.audit): why THIS model
        # left residency, without holding a reference to it
        _audit.record_decision(
            "eviction", "serving", "evicted", subject=name,
            tenant=f"serving:{name}",
            reason=reason, estimate_bytes=entry.resident_bytes,
        )
        # the program (and its device state) are the only HBM pins; the
        # shared-ledger claim returns with them (docs/scheduling.md)
        memory.release_admission(entry.admission)
        entry.program = None
        if entry.degraded_admission is not None:
            memory.release_admission(entry.degraded_admission)
            entry.degraded_program = None
        if telemetry.enabled():
            reg = telemetry.registry()
            reg.inc("serve.model_evictions")
            reg.gauge("serve.resident_bytes", self.resident_bytes())
            reg.gauge("serve.resident_models", len(self._entries))
        self._logger.info("evicted serving model %r (%s)", name, reason)
