#
# The persistent serving plane: resident multi-model scoring (docs/serving.md).
#
# The reference's serving story (PAPER.md L5) re-enters Python and
# re-dispatches a `pandas_udf` per query batch. This package composes what
# the fit side already built — bucket-padded predict programs + the
# persistent compile cache (PR 4), the HBM admission budgeter (PR 7), and
# the tiled distance core (PR 10) — into a long-lived scoring service:
#
#   * `ModelRegistry` — many fitted models RESIDENT in HBM at once, each
#     loaded under a `memory.admit_model_load` verdict (params placement +
#     per-bucket predict workspace, exactly like fits; over-budget loads
#     evict LRU residents or refuse typed with `HbmBudgetError`), with the
#     bucket ladder's predict programs prewarmed at load time so the first
#     query is compile-free;
#   * `ScoringEngine` — concurrent predict requests, coalesced up the
#     geometric bucket ladder inside a bounded window (micro-batching),
#     dispatched async (`block_until_ready` only at response assembly), and
#     sliced back out per request — bit-identical to serving each request
#     solo.
#
# The async contract is CI-enforced: the ci/analysis `serve-dispatch` rule
# forbids direct `jit`/`block_until_ready`/`device_get` in this package
# outside the engine's one response-assembly point (`# serve-ok: <reason>`).
#
from .engine import ScoreFuture, ScoringEngine  # noqa: F401
from .overload import OverloadController  # noqa: F401
from .registry import ModelRegistry, ResidentModel  # noqa: F401

__all__ = [
    "ModelRegistry",
    "ResidentModel",
    "ScoringEngine",
    "ScoreFuture",
    "OverloadController",
]
