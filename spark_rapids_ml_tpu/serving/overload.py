#
# Overload control for the serving plane: deadline-aware admission, the
# SLO-closed-loop backpressure ladder, and the adaptive micro-batching
# planner (docs/serving.md "Overload & backpressure").
#
# The reference stack leans on Spark's task scheduler to backpressure work
# onto the accelerators; the resident ScoringEngine (PR 11) had no such
# supervisor — an open-loop queue that trusted every caller. This module is
# the closed loop, built from machinery that already exists:
#
#   * ADMISSION (per request, synchronous at submit): the bounded queue
#     (`config["serve_max_queue_rows"]`), the deadline-feasibility check
#     against the live windowed `serve.queue_wait_s` p99, and the tenant's
#     ladder gate — refusals are typed `ServeOverloadError`s carrying their
#     evidence (queue depth, predicted wait, deadline, ladder level).
#   * THE LADDER (per tenant, evaluated on the dispatch path): a tenant
#     burning its serving latency budget — per-tenant burn via
#     `ops_plane.slo.burn_rate` over the tenant histogram siblings, or the
#     global spec verdict from `ops_plane.slo.last_verdicts` — walks
#     healthy -> throttle (token bucket) -> degrade (the registry's
#     `serve_degraded_dtype` rung, where `_serve_dtypes` allows) -> shed,
#     one rung per hysteresis hold (`config["serve_overload_hold_s"]`), and
#     back down one rung per hold once the burn clears. Every transition is
#     recorded through `ops_plane.audit` (kind "backpressure") and the
#     flight recorder — the scheduler's audited-decision contract.
#   * ADAPTIVE BATCHING (pure planners, unit-testable): under congestion
#     (queue-wait p99 above the static window) the coalesce window grows
#     toward `serve_coalesce_window_ceiling_ms` so saturation builds fuller
#     batches instead of longer queues; uncongested traffic keeps the
#     static window EXACTLY (static values remain as overrides), and a zero
#     window still disables coalescing entirely.
#
# Everything here reads clocks via time.monotonic() — deadlines and holds
# must survive wall-clock steps (the wallclock-deadline analysis rule pins
# the contract framework-wide).
#
from __future__ import annotations

import time
import weakref
from dataclasses import dataclass
from typing import Any, Dict, Optional

from .. import telemetry
from ..errors import ServeOverloadError
from ..utils import get_logger, lockcheck

__all__ = [
    "LEVELS",
    "LEVEL_HEALTHY",
    "LEVEL_THROTTLE",
    "LEVEL_DEGRADE",
    "LEVEL_SHED",
    "OverloadController",
    "plan_window",
    "plan_target_rows",
    "serving_report",
]

# The degradation ladder, mild to severe. Index IS the level.
LEVELS = ("healthy", "throttle", "degrade", "shed")

# Queue depth below which admission's backlog/service-rate wait estimate is
# ignored (the windowed rate is too idle-biased to price a short queue).
_BACKLOG_MIN_DEPTH = 4
LEVEL_HEALTHY, LEVEL_THROTTLE, LEVEL_DEGRADE, LEVEL_SHED = range(4)


# ------------------------------------------------------- batching planners --


def plan_window(
    base_s: float,
    *,
    floor_s: float,
    ceiling_s: float,
    arrival_rows_per_s: Optional[float],
    queue_rows: int,
    queue_wait_p99_s: Optional[float],
    max_rows: int,
) -> float:
    """The adaptive coalesce window (seconds), pure arithmetic.

    Invariants (pinned by tests/test_serving_overload.py):
      * ``base_s <= 0`` -> 0.0: an explicit zero window means NO coalescing,
        adaptive or not.
      * uncongested (queue-wait p99 absent or at/under the static window)
        -> exactly ``base_s``: static behavior until there is congestion
        evidence, so a configured window is an override, not a hint.
      * congested with the queue already holding a full batch -> the floor:
        waiting adds latency but no batch size.
      * otherwise -> the time to FILL one max batch at the observed arrival
        rate, clamped to [base, ceiling]: saturation grows batches.
    """
    if base_s <= 0.0:
        return 0.0
    hi = max(float(ceiling_s), base_s)
    lo = min(max(float(floor_s), 0.0), base_s)
    if queue_wait_p99_s is None or queue_wait_p99_s <= base_s:
        return base_s
    if queue_rows >= max_rows:
        return lo
    if not arrival_rows_per_s or arrival_rows_per_s <= 0.0:
        return base_s
    fill_s = (max_rows - queue_rows) / arrival_rows_per_s
    return min(max(base_s, fill_s), hi)


def plan_target_rows(
    *,
    min_rows: int,
    max_rows: int,
    queue_rows: int,
    arrival_rows_per_s: Optional[float],
    window_s: float,
    congested: bool,
) -> int:
    """The coalesce row target: how many rows a micro-batch aims to collect
    before dispatching. Uncongested -> ``max_rows`` (static behavior: the
    window, not the target, bounds the batch). Congested -> the geometric
    bucket-ladder rung covering the rows expected in one window (queued
    backlog + window's arrivals), so dispatches land on prewarmed bucket
    shapes instead of arbitrary sizes — still clamped to ``max_rows``."""
    if not congested:
        return max_rows
    expect = queue_rows
    if arrival_rows_per_s and arrival_rows_per_s > 0.0 and window_s > 0.0:
        expect += int(arrival_rows_per_s * window_s)
    if expect >= max_rows:
        return max_rows
    rung = max(1, int(min_rows))
    while rung < expect:
        rung *= 2
    return min(rung, max_rows)


# ------------------------------------------------------------- the ladder --


@dataclass
class _TenantState:
    level: int = LEVEL_HEALTHY
    since: float = 0.0  # monotonic time of the last transition
    burn: Optional[float] = None  # newest per-tenant burn observed
    tokens: float = 0.0  # throttle rung's token bucket (rows)
    refilled: float = 0.0  # monotonic time of the last refill
    transitions: int = 0
    shed: int = 0
    throttled: int = 0
    degraded: int = 0


# Live controllers, for `serving_report()` (ops_plane.report's "serving"
# section): weakly held so an engine's end-of-life does not need an
# unregister call.
_CONTROLLERS: "weakref.WeakSet[OverloadController]" = weakref.WeakSet()


class OverloadController:
    """Per-engine admission gate + per-tenant degradation ladder.

    One instance per ScoringEngine; thread-safe (submit threads call
    `admit`, the worker thread calls `maybe_evaluate`). All config is read
    per call so tests (and live operators) can retune without rebuilding
    the engine."""

    def __init__(self) -> None:
        self._lock = lockcheck.make_lock("serving.overload.OverloadController._lock")
        self._tenants: Dict[str, _TenantState] = {}  # guarded-by: _lock
        self._last_eval = 0.0  # guarded-by: _lock
        self._logger = get_logger(type(self))
        _CONTROLLERS.add(self)

    # ------------------------------------------------------------- admit --
    def admit(
        self,
        *,
        model: str,
        tenant: str,
        rows: int,
        deadline_s: Optional[float],
        now: float,
        queue_depth: int,
        queue_rows: int,
    ) -> bool:
        """Admission-or-refusal for one request, BEFORE it queues. Returns
        whether the tenant's ladder level asks for the degraded rung (the
        engine honors it only when the resident entry has one). Raises
        `ServeOverloadError` (shed / throttle / queue bound / predicted
        wait), ticking the matching `serve.*` counter."""
        from ..core import config

        reg = telemetry.registry() if telemetry.enabled() else None
        st = self._state(tenant, now)
        level = st.level
        # --- ladder gate: shed refuses outright, throttle meters rows -----
        if level >= LEVEL_SHED:
            with self._lock:
                st.shed += 1
            if reg is not None:
                reg.inc("serve.shed_requests")
            raise ServeOverloadError(
                f"request for model {model!r} shed: tenant {tenant!r} is "
                "over its latency budget",
                model=model, tenant=tenant, level=LEVELS[level],
                queue_depth=queue_depth, queue_rows=queue_rows,
            )
        if level >= LEVEL_THROTTLE and rows > 0:
            if not self._take_tokens(st, tenant, rows, now, reg):
                with self._lock:
                    st.throttled += 1
                if reg is not None:
                    reg.inc("serve.throttled_requests")
                raise ServeOverloadError(
                    f"request for model {model!r} throttled: tenant "
                    f"{tenant!r}'s token bucket is empty",
                    model=model, tenant=tenant, level=LEVELS[level],
                    queue_depth=queue_depth, queue_rows=queue_rows,
                )
        # --- bounded queue ------------------------------------------------
        max_queue_rows = int(config.get("serve_max_queue_rows", 262144))
        if max_queue_rows > 0 and queue_rows + rows > max_queue_rows:
            if reg is not None:
                reg.inc("serve.rejected_requests")
            raise ServeOverloadError(
                f"request for model {model!r} refused: the serving queue is "
                f"full ({queue_rows} + {rows} rows against a "
                f"{max_queue_rows}-row bound)",
                model=model, tenant=tenant, level=LEVELS[level],
                queue_depth=queue_depth, queue_rows=queue_rows,
            )
        # --- deadline feasibility against the live wait prediction --------
        # Two signals, take the worse: the windowed queue-wait p99 (what
        # dispatched requests actually waited), and backlog / service rate
        # (what the CURRENT queue implies). The p99 alone is survivorship-
        # biased under saturation — only requests that waited less than
        # their deadline ever dispatch and record a wait, so a queue whose
        # backlog exceeds every deadline would keep predicting "feasible"
        # while 100% of admissions expire at the head.
        if deadline_s is not None and reg is not None:
            fast_w = reg.bucket_seconds() * 3.0
            p99 = reg.window_quantile("serve.queue_wait_s", 0.99, fast_w)
            service = reg.rate("serve.rows", fast_w)
            # The backlog estimate needs PRESSURE to be meaningful: the
            # windowed rate counts idle time as service time, so a
            # nearly-empty window under light load predicts absurd waits
            # for a one-request queue. A few requests deep is the signal
            # that the queue is actually contended.
            backlog_s = (
                queue_rows / service
                if service and queue_rows > 0 and queue_depth >= _BACKLOG_MIN_DEPTH
                else None
            )
            candidates = [w for w in (p99, backlog_s) if w is not None]
            predicted = max(candidates) if candidates else None
            if predicted is not None and now + predicted > deadline_s:
                reg.inc("serve.rejected_requests")
                raise ServeOverloadError(
                    f"request for model {model!r} refused: the live queue "
                    "wait predicts the deadline cannot be met",
                    model=model, tenant=tenant, level=LEVELS[level],
                    queue_depth=queue_depth, queue_rows=queue_rows,
                    predicted_wait_ms=predicted * 1e3,
                    deadline_ms=max(0.0, (deadline_s - now)) * 1e3,
                )
        if level >= LEVEL_DEGRADE:
            with self._lock:
                st.degraded += 1
            return True
        return False

    def _take_tokens(
        self, st: _TenantState, tenant: str, rows: int,
        now: float, reg: Any,
    ) -> bool:
        """Refill-then-take on the tenant's token bucket. Rate =
        `config["serve_throttle_rows_per_s"]`, or (auto, 0) half the
        tenant's recent admitted row rate; no measurable rate yet means no
        metering (the ladder just escalated — refusing everything before
        the first refill would be a shed, not a throttle). Burst capacity
        is one second of rate."""
        from ..core import config

        rate = float(config.get("serve_throttle_rows_per_s", 0.0))
        if rate <= 0.0:
            if reg is None:
                return True
            got = reg.rate(
                telemetry.tenant_metric("serve.rows", tenant),
                reg.bucket_seconds() * 3.0,
            )
            if not got:
                return True
            rate = max(1.0, 0.5 * got)
        with self._lock:
            if st.refilled <= 0.0:
                st.tokens, st.refilled = rate, now  # first fill: 1s burst
            else:
                st.tokens = min(rate, st.tokens + (now - st.refilled) * rate)
                st.refilled = now
            if st.tokens < rows:
                return False
            st.tokens -= rows
            return True

    # ---------------------------------------------------------- evaluate --
    def maybe_evaluate(self, now: Optional[float] = None) -> None:
        """The dispatch-path hook (mirrors `slo.maybe_evaluate`): ladder
        evaluation throttled to one pass per metrics bucket width, a no-op
        without a configured serving latency SLO spec, and never raising
        into the hot path."""
        try:
            from ..ops_plane import slo as _slo

            spec = _slo.serving_latency_spec()
            if spec is None or not telemetry.enabled():
                return
            reg = telemetry.registry()
            t = time.monotonic() if now is None else now
            with self._lock:
                if t - self._last_eval < min(reg.bucket_seconds(), self._hold_s()):
                    return
                self._last_eval = t
            self.evaluate(spec, now=t)
        except Exception:  # pragma: no cover - the ladder never fails serving
            self._logger.debug("overload evaluation failed", exc_info=True)

    def evaluate(self, spec: Dict[str, Any], *, now: Optional[float] = None) -> None:
        """One ladder pass: recompute every known tenant's burn and walk
        each one rung up (burning) or down (clear), hysteresis-guarded —
        at most one transition per tenant per `serve_overload_hold_s`
        dwell. Public so tests and ops drills can force a pass."""
        t = time.monotonic() if now is None else now
        hold = self._hold_s()
        global_failing = self._global_failing(spec)
        with self._lock:
            tenants = list(self._tenants)
        for tenant in tenants:
            burn = self._tenant_burn(tenant, spec)
            burning = bool(
                (burn is not None and burn >= self._fast_factor(spec))
                or (global_failing and burn is not None)
            )
            event = None
            with self._lock:
                st = self._tenants[tenant]
                st.burn = burn
                level = st.level
                dwelled = (t - st.since) >= hold
                if burning and level < LEVEL_SHED and (level == LEVEL_HEALTHY or dwelled):
                    event = self._transition_locked(st, tenant, level + 1, t, burn)
                elif not burning and level > LEVEL_HEALTHY and dwelled:
                    event = self._transition_locked(st, tenant, level - 1, t, burn)
            if event is not None:
                self._record_transition(event)

    def _transition_locked(
        self, st: _TenantState, tenant: str, to_level: int,
        now: float, burn: Optional[float],
    ) -> Dict[str, Any]:
        """Mutate one tenant's ladder state under `_lock`; returns the
        transition event for `_record_transition` to emit OUTSIDE the lock
        (audit/recorder/telemetry take their own locks)."""
        from_level = st.level
        st.level, st.since, st.transitions = to_level, now, st.transitions + 1
        if to_level == LEVEL_HEALTHY:
            st.tokens, st.refilled = 0.0, 0.0  # bucket resets with the ladder
        return {
            "tenant": tenant,
            "from_level": from_level,
            "to_level": to_level,
            "burn": burn,
            "max_level": max(s.level for s in self._tenants.values()),
        }

    def _record_transition(self, event: Dict[str, Any]) -> None:
        from .. import diagnostics
        from ..ops_plane import audit as _audit

        tenant = event["tenant"]
        from_level, to_level = event["from_level"], event["to_level"]
        burn = event["burn"]
        verdict = LEVELS[to_level] if to_level > from_level else "restore"
        reason = (
            f"latency burn {burn:.2f}" if burn is not None else "burn cleared"
        )
        # the audited-decision contract: every throttle/degrade/shed/restore
        # lands in the bounded decision log AND the flight recorder
        _audit.record_decision(
            "backpressure", "serving", verdict, subject=tenant, tenant=tenant,
            reason=f"{reason}; {LEVELS[from_level]} -> {LEVELS[to_level]}",
            from_level=LEVELS[from_level], to_level=LEVELS[to_level],
            burn=burn,
        )
        diagnostics.record_event(
            "serve.backpressure", tenant=tenant, verdict=verdict,
            from_level=LEVELS[from_level], to_level=LEVELS[to_level], burn=burn,
        )
        if telemetry.enabled():
            reg = telemetry.registry()
            reg.inc("serve.backpressure_transitions")
            reg.gauge(
                telemetry.tenant_metric("serve.overload_level", tenant),
                float(to_level),
            )
            reg.gauge("serve.overload_level", float(event["max_level"]))
        self._logger.warning(
            "backpressure %s: tenant %r %s -> %s (%s)",
            verdict, tenant, LEVELS[from_level], LEVELS[to_level], reason,
        )

    # ------------------------------------------------------------ signals --
    def _tenant_burn(self, tenant: str, spec: Dict[str, Any]) -> Optional[float]:
        """Per-tenant burn of the configured serving latency objective, read
        from the tenant's histogram sibling over the spec's fast window.
        Overridable seam: the hysteresis tests script it."""
        from ..ops_plane import slo as _slo

        if not telemetry.enabled():
            return None
        hist = telemetry.tenant_metric(str(spec.get("histogram", "")), tenant)
        fast_w = float(spec.get("fast_window_s", _slo.DEFAULT_FAST_WINDOW_S))
        return _slo.burn_rate(
            hist,
            threshold_s=float(spec.get("threshold_s", 0.0)),
            objective=float(spec.get("objective", 0.99)),
            window_s=fast_w,
        )

    @staticmethod
    def _fast_factor(spec: Dict[str, Any]) -> float:
        from ..ops_plane import slo as _slo

        return float(spec.get("fast_burn", _slo.DEFAULT_FAST_BURN))

    @staticmethod
    def _global_failing(spec: Dict[str, Any]) -> bool:
        """Whether the configured spec's GLOBAL verdict is currently
        failing (`slo.last_verdicts`) — escalates every tenant with window
        traffic, so a fleet-wide burn does not hide behind per-tenant
        budgets."""
        from ..ops_plane import slo as _slo

        name = str(spec.get("name") or spec.get("kind") or "slo")
        return any(
            v.get("failing") for v in _slo.last_verdicts() if v.get("name") == name
        )

    def _hold_s(self) -> float:
        from ..core import config

        return max(0.0, float(config.get("serve_overload_hold_s", 30.0)))

    def _state(self, tenant: str, now: float) -> _TenantState:
        with self._lock:
            st = self._tenants.get(tenant)
            if st is None:
                st = self._tenants[tenant] = _TenantState(since=now)
            return st

    # -------------------------------------------------------------- views --
    def level(self, tenant: str) -> int:
        with self._lock:
            st = self._tenants.get(tenant)
            return st.level if st is not None else LEVEL_HEALTHY

    def force_level(self, tenant: str, level: int) -> None:
        """Pin a tenant's ladder level (tests, ops drills). Audited like an
        organic transition so a drill leaves the same evidence."""
        t = time.monotonic()
        st = self._state(tenant, t)
        with self._lock:
            if st.level == level:
                return
            event = self._transition_locked(st, tenant, int(level), t, st.burn)
        self._record_transition(event)

    def stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-tenant ladder state for `ScoringEngine.stats()` and the ops
        report: level, newest burn, dwell, and the refusal counters."""
        t = time.monotonic()
        with self._lock:
            return {
                tenant: {
                    "level": LEVELS[st.level],
                    "burn": st.burn,
                    "dwell_s": t - st.since if st.since else 0.0,
                    "transitions": st.transitions,
                    "shed_requests": st.shed,
                    "throttled_requests": st.throttled,
                    "degraded_requests": st.degraded,
                }
                for tenant, st in self._tenants.items()
            }


def serving_report() -> Dict[str, Any]:
    """The ops-plane `report()`s "serving" section: every live controller's
    per-tenant ladder state plus the per-tenant latency summaries read back
    through the `telemetry.tenant_metric` naming contract."""
    tenants: Dict[str, Any] = {}
    for ctl in list(_CONTROLLERS):
        tenants.update(ctl.stats())
    for tenant, view in tenants.items():
        for base in ("serve.queue_wait_s", "serve.e2e_s"):
            s = telemetry.summarize_histogram(telemetry.tenant_metric(base, tenant))
            key = base.split(".", 1)[1].rsplit("_s", 1)[0]
            view[f"{key}_p50_s"] = s["p50"]
            view[f"{key}_p99_s"] = s["p99"]
    return {"tenants": tenants}
