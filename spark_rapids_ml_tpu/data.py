#
# Data plane: DataFrame-like input -> contiguous numpy dense / scipy CSR blocks,
# ready for HBM placement as sharded `jax.Array`s.
#
# Mirrors the reference's L2 ingest (reference core.py:458-557 input pre-processing,
# core.py:205-250 sparse-vector decode, core.py:698-760 Arrow-batch -> numpy/CSR
# loop), re-designed for the TPU build: instead of per-batch pandas conversion
# inside a Spark UDF, the ingest produces one contiguous (row-major) feature block
# per partition that the parallel layer pads and lays out on the device mesh.
#
# Accepted dataset types: pandas.DataFrame, pyarrow.Table, dict[str, array-like],
# and (when pyspark is installed) pyspark.sql.DataFrame via collection to Arrow.
#
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence, Tuple, Union

import numpy as np

from .linalg import DenseVector, SparseVector

try:  # scipy is available in this image; used for the CSR ingest path
    import scipy.sparse as _sp
except Exception:  # pragma: no cover
    _sp = None


@dataclass
class ExtractedData:
    """Columnar view of a dataset after ingest."""

    features: Any  # np.ndarray [n, d] or scipy.sparse.csr_matrix
    label: Optional[np.ndarray] = None
    weight: Optional[np.ndarray] = None
    row_id: Optional[np.ndarray] = None
    feature_kind: str = "array"  # "vector" | "array" | "multi_cols"
    feature_names: List[str] = field(default_factory=list)
    # source column names for validation error attribution (the streaming
    # path validates per row-block long after extraction, so the names must
    # ride along with the data)
    label_name: Optional[str] = None
    weight_name: Optional[str] = None

    @property
    def n_rows(self) -> int:
        return int(self.features.shape[0])

    @property
    def n_cols(self) -> int:
        return int(self.features.shape[1])

    @property
    def is_sparse(self) -> bool:
        return _sp is not None and _sp.issparse(self.features)


def as_pandas(dataset: Any):
    """Normalize any accepted dataset type to a pandas DataFrame (zero-copy where possible)."""
    import pandas as pd

    if isinstance(dataset, pd.DataFrame):
        return dataset
    try:
        import pyarrow as pa

        if isinstance(dataset, pa.Table):
            return dataset.to_pandas()
    except ImportError:  # pragma: no cover
        pass
    if isinstance(dataset, dict):
        return pd.DataFrame({k: (list(v) if getattr(v, "ndim", 1) > 1 else v) for k, v in dataset.items()})
    # pyspark.sql.DataFrame (optional dependency)
    if hasattr(dataset, "toPandas") and hasattr(dataset, "sparkSession"):
        return dataset.toPandas()
    raise TypeError(f"Unsupported dataset type {type(dataset)}; expected pandas/pyarrow/dict")


def dataset_fingerprint(dataset: Any) -> tuple:
    """Identity fingerprint of a dataset object, for DeviceDataset cache keys
    (core.device_dataset_scope).

    Identity-based BY DESIGN: it never hashes the data (a content hash of a
    multi-GiB block would cost a full host pass per fit — more than the
    ingest it is meant to skip), so it is exact for the reuse it serves —
    repeated fits over the SAME object inside one scope (CV folds, sweep
    refits). The id() is only stable while the object is alive, so every
    cache entry PINS its source object (`DeviceDataset.source`) — without
    that, a recycled id on a new same-shaped object would be a silent false
    hit. Shape/columns ride along as defense in depth. An in-place mutation
    of the same object between fits inside one scope is not detected
    (documented in docs/performance.md)."""
    if isinstance(dataset, dict):
        shapes = tuple(
            (str(k), tuple(getattr(v, "shape", ())) or (len(v) if hasattr(v, "__len__") else None))
            for k, v in dataset.items()
        )
        return (id(dataset), type(dataset).__name__, shapes)
    cols = getattr(dataset, "columns", None)
    cols_t = tuple(map(str, cols)) if cols is not None else None
    shape = getattr(dataset, "shape", None)
    if shape is None and hasattr(dataset, "__len__"):
        shape = (len(dataset),)
    return (id(dataset), type(dataset).__name__, cols_t, tuple(shape) if shape else None)


def same_ingest_identity(key_a: Any, key_b: Any) -> bool:
    """Whether two DeviceDataset cache keys name the SAME ingested data —
    dataset fingerprint, extraction columns, dtype/sparse mode — regardless
    of the MESH they were placed on (the key's final component). This is the
    host-retained re-placement predicate for elastic recovery
    (docs/robustness.md): after a survivor re-mesh changes the device set,
    the stale placement's `extracted` host blocks are still the right data —
    only the layout must be redone on the new mesh."""
    return (
        key_a is not None
        and key_b is not None
        and len(key_a) == len(key_b) == 4
        and key_a[:3] == key_b[:3]
    )


def ingest_chunk_rows(row_bytes: int) -> int:
    """Rows per ingest chunk under ``core.config["ingest_chunk_bytes"]``."""
    from .core import config  # lazy: core imports this module at load time

    chunk_bytes = int(config.get("ingest_chunk_bytes", 128 << 20))
    return max(1, chunk_bytes // max(1, int(row_bytes)))


def _first_nonfinite_row(block: np.ndarray, lo: int) -> int:
    """Row index (absolute, given chunk offset `lo`) of the first non-finite
    entry in a dense chunk."""
    finite_rows = np.isfinite(block).all(axis=tuple(range(1, block.ndim)))
    return lo + int(np.argmin(finite_rows))


def validate_extracted(
    extracted: "ExtractedData",
    label_col=None,
    weight_col=None,
    lo: int = 0,
    hi: Optional[int] = None,
) -> None:
    """NaN/Inf scan over rows ``[lo, hi)`` of the ingested blocks.

    Chunked under the same ``ingest_chunk_bytes`` bound as the ingest itself,
    so validation temporaries (the per-chunk finite mask) never scale with
    the dataset. Raises `IngestValidationError` NAMING the offending column
    and the ABSOLUTE first bad row — the alternative is a NaN surfacing
    iterations later inside a solver as a divergence with no pointer back to
    the data. The full-range call is the eager fit-entry scan; the streaming
    fit path calls it PER ROW-BLOCK as chunks enter the pipeline, so the
    dataset is never host-materialized a second time just to validate it."""
    from .core import config
    from .errors import IngestValidationError

    feats = extracted.features
    n = extracted.n_rows
    hi = n if hi is None else min(int(hi), n)
    lo = max(0, int(lo))
    if extracted.is_sparse:
        # CSR: only the stored values can be non-finite; chunk the row range's
        # data slice and map the first bad element back to its ABSOLUTE row
        # through indptr
        indptr = feats.indptr
        e_lo, e_hi = int(indptr[lo]), int(indptr[hi])
        data = feats.data
        step = max(1, int(config.get("ingest_chunk_bytes", 128 << 20)) // max(1, data.itemsize))
        for elo in range(e_lo, e_hi, step):
            chunk = data[elo : min(elo + step, e_hi)]
            if not np.isfinite(chunk).all():
                elem = elo + int(np.argmin(np.isfinite(chunk)))
                row = int(np.searchsorted(indptr, elem, side="right") - 1)
                raise IngestValidationError(extracted.feature_names[0], row)
    else:
        # drift seedling (ops_plane.drift, docs/observability.md "Ops
        # plane"): per-column moments + PSI bins accumulate off this SAME
        # pass — zero extra data reads; stats for a failing chunk are taken
        # BEFORE the raise (partial stats are never published). None (and
        # zero cost) while telemetry is off or the block is sparse.
        from .ops_plane import drift as _drift

        acc = _drift.accumulator_for(extracted)
        row_bytes = feats.shape[1] * feats.itemsize if feats.ndim > 1 else feats.itemsize
        step = ingest_chunk_rows(row_bytes)
        for clo in range(lo, hi, step):
            chunk = np.asarray(feats[clo : min(clo + step, hi)])
            if acc is not None:
                acc.update(chunk)
            if np.isfinite(chunk).all():
                continue
            if extracted.feature_kind == "multi_cols" and chunk.ndim > 1:
                # name the exact offending source column, not the block
                bad_cols = ~np.isfinite(chunk).all(axis=0)
                name = extracted.feature_names[int(np.argmax(bad_cols))]
                col = chunk[:, int(np.argmax(bad_cols))]
                raise IngestValidationError(name, clo + int(np.argmin(np.isfinite(col))))
            raise IngestValidationError(
                extracted.feature_names[0], _first_nonfinite_row(chunk, clo)
            )
        if acc is not None and acc.rows >= n:
            # the whole dataset has been scanned (eagerly, or as the last of
            # the streaming path's per-row-block calls): publish the
            # ingest.feature.* gauges (+ PSI when a baseline is registered)
            acc.publish()
    for name, arr in ((label_col, extracted.label), (weight_col, extracted.weight)):
        if arr is None:
            continue
        part = arr[lo:hi]
        if not np.isfinite(part).all():
            raise IngestValidationError(
                str(name), lo + int(np.argmin(np.isfinite(part)))
            )


def run_deferred_validation(
    extracted: "ExtractedData", lo: int = 0, hi: Optional[int] = None
) -> None:
    """`validate_extracted` gated on ``config["validate_ingest"]``, with the
    column names taken from the extraction record — the entry point for the
    fit driver (eager full scan on the resident path) and the streaming
    pipeline (per row-block)."""
    from .core import config

    if not config.get("validate_ingest", False):
        return
    validate_extracted(
        extracted, extracted.label_name, extracted.weight_name, lo=lo, hi=hi
    )


def _validate_ingest(
    extracted: "ExtractedData", label_col=None, weight_col=None
) -> None:
    """Opt-in eager NaN/Inf scan at extraction (``config["validate_ingest"]``)."""
    from .core import config

    if not config.get("validate_ingest", False):
        return
    validate_extracted(extracted, label_col, weight_col)


def _record_ingest(
    extracted: "ExtractedData", label_col=None, weight_col=None, validate: bool = True
) -> "ExtractedData":
    """Validation (opt-in, deferrable) + telemetry counters for a completed
    extraction: rows and host bytes staged (CSR counts its data+index
    arrays). The telemetry half is a flag-checked no-op when disabled.
    ``validate=False`` DEFERS the NaN/Inf scan to the caller (the fit driver:
    eager full scan on the resident path, per row-block on the streaming
    path — `run_deferred_validation`)."""
    from . import telemetry

    extracted.label_name = None if label_col is None else str(label_col)
    extracted.weight_name = None if weight_col is None else str(weight_col)
    if validate:
        _validate_ingest(extracted, label_col=label_col, weight_col=weight_col)
    if telemetry.enabled():
        feats = extracted.features
        if extracted.is_sparse:
            nbytes = feats.data.nbytes + feats.indices.nbytes + feats.indptr.nbytes
        else:
            nbytes = feats.nbytes
        for aux in (extracted.label, extracted.weight, extracted.row_id):
            if aux is not None:
                nbytes += aux.nbytes
        reg = telemetry.registry()
        reg.inc("ingest.rows", extracted.n_rows)
        reg.inc("ingest.bytes", nbytes)
        reg.inc("ingest.datasets")
    return extracted


def _fill_dense_chunked(values, n_cols: int, dtype, to_row) -> np.ndarray:
    """Object column of per-row vectors -> preallocated [n, n_cols] block,
    converted one row-chunk at a time (chunk size bounded by
    ``core.config["ingest_chunk_bytes"]``) so the per-row temporaries never
    exceed one chunk — the old whole-column ``np.stack`` held a full second
    copy of the dataset in flight."""
    from . import telemetry

    n = len(values)
    out = np.empty((n, n_cols), dtype=dtype)
    step = ingest_chunk_rows(n_cols * np.dtype(dtype).itemsize)
    for lo in range(0, n, step):
        hi = min(lo + step, n)
        out[lo:hi] = [to_row(v) for v in values[lo:hi]]
        telemetry.registry().inc("ingest.chunks")
    return out


def _column_to_matrix(col, dtype) -> Tuple[Any, str]:
    """Convert a single feature column (vectors / arrays / lists) to a 2-D block.

    Returns (matrix, kind) where kind is 'vector' when the column held
    Dense/SparseVector objects (so transform can emit vectors back) else 'array'.
    Sparse rows produce a scipy CSR matrix. Dense conversion runs row-chunk by
    row-chunk (``ingest_chunk_bytes``); the sparse path counts nnz first and
    fills preallocated CSR arrays in place (no second full-nnz copy).
    """
    values = col.to_numpy() if hasattr(col, "to_numpy") else np.asarray(col, dtype=object)
    if len(values) == 0:
        raise ValueError("empty feature column")
    first = values[0]
    if isinstance(first, (DenseVector, SparseVector)) or (
        _sp is not None and _sp.issparse(first)
    ):
        any_sparse = any(
            isinstance(v, SparseVector) or (_sp is not None and _sp.issparse(v)) for v in values
        )
        if any_sparse:
            size = first.size if isinstance(first, (DenseVector, SparseVector)) else first.shape[1]
            n = len(values)

            def _row_parts(v):
                if isinstance(v, SparseVector):
                    return v.indices, v.values
                if isinstance(v, DenseVector):
                    idx = np.nonzero(v.values)[0].astype(np.int32)
                    return idx, v.values[idx]
                v = v.tocsr()  # scipy sparse row
                return v.indices, v.data

            # decode each row ONCE (SparseVector rows contribute pure
            # references to their own index/value arrays — no copy), size the
            # CSR arrays from the decoded lengths, then fill in place, freeing
            # the decoded Dense/scipy-row copies as they are consumed — no
            # second full-nnz concatenate copy ever exists
            parts = [_row_parts(v) for v in values]
            indptr = np.zeros(n + 1, dtype=np.int64)
            for i, (idx, _) in enumerate(parts):
                indptr[i + 1] = indptr[i] + len(idx)
            data = np.empty(int(indptr[-1]), dtype=dtype)
            indices = np.empty(int(indptr[-1]), dtype=np.int32)
            for i in range(n):
                idx, val = parts[i]
                parts[i] = None  # free decode copies as they are copied in
                lo, hi = indptr[i], indptr[i + 1]
                indices[lo:hi] = idx
                data[lo:hi] = val  # cast to dtype on assignment
            mat = _sp.csr_matrix(
                (data, indices, indptr), shape=(n, size), dtype=dtype
            )
            return mat, "vector"
        return _fill_dense_chunked(values, first.size, dtype, lambda v: v.toArray()), "vector"
    # plain array/list rows
    if isinstance(first, np.ndarray) and first.ndim == 1:
        return _fill_dense_chunked(values, len(first), dtype, lambda v: v), "array"
    if isinstance(first, (list, tuple)):
        return _fill_dense_chunked(values, len(first), dtype, np.asarray), "array"
    raise TypeError(f"Unsupported feature cell type {type(first)} in feature column")


def extract_dataset(
    dataset: Any,
    *,
    input_col: Optional[str] = None,
    input_cols: Optional[Sequence[str]] = None,
    label_col: Optional[str] = None,
    weight_col: Optional[str] = None,
    id_col: Optional[str] = None,
    float32_inputs: bool = True,
    enable_sparse_data_optim: Optional[bool] = None,
    validate: bool = True,
) -> ExtractedData:
    """Extract features (+label/weight/id) as contiguous blocks.

    ``enable_sparse_data_optim``: None autodetects (CSR kept sparse); True requires
    a sparse input (raises otherwise); False densifies (reference params.py:44-65).
    ``validate=False`` defers the opt-in NaN/Inf scan to the caller (see
    `_record_ingest`).
    """
    dtype = np.float32 if float32_inputs else np.float64

    # Fast path for dict datasets whose feature entry is ALREADY a 2-D block
    # (ndarray or scipy CSR): skip the per-row object column entirely. This is
    # the at-scale ingest used by the benchmark suite — the reference reads
    # parquet into whole Arrow batches the same way (core.py:724-760) rather
    # than per-row vectors.
    if (
        isinstance(dataset, dict)
        and input_col is not None
        and input_col in dataset
        and (
            (isinstance(dataset[input_col], np.ndarray) and dataset[input_col].ndim == 2)
            or (_sp is not None and _sp.issparse(dataset[input_col]))
        )
    ):
        features = dataset[input_col]
        if _sp is not None and _sp.issparse(features):
            features = features.tocsr()
            if enable_sparse_data_optim is False:
                features = np.asarray(features.todense(), dtype=dtype)
            kind = "vector"
        else:
            features = np.ascontiguousarray(features, dtype=dtype)
            kind = "array"
            if enable_sparse_data_optim is True:
                raise ValueError("enable_sparse_data_optim=True requires sparse input")

        def _dict_scalar(colname, dt):
            if colname is None or colname == "":
                return None
            if colname not in dataset:
                raise ValueError(f"column {colname!r} not in dataset")
            return np.asarray(dataset[colname], dtype=dt)

        return _record_ingest(ExtractedData(
            features=features,
            label=_dict_scalar(label_col, dtype),
            weight=_dict_scalar(weight_col, dtype),
            row_id=_dict_scalar(id_col, np.int64),
            feature_kind=kind,
            feature_names=[input_col],
        ), label_col=label_col, weight_col=weight_col, validate=validate)

    pdf = as_pandas(dataset)

    if input_cols is not None:
        missing = [c for c in input_cols if c not in pdf.columns]
        if missing:
            raise ValueError(f"feature columns not in dataset: {missing}")
        names = list(input_cols)
        # chunked column->block conversion: the whole-frame to_numpy holds a
        # second full copy in flight; filling a preallocated block per
        # row-chunk bounds the temporary at one chunk
        n = len(pdf)
        features = np.empty((n, len(names)), dtype=dtype)
        step = ingest_chunk_rows(len(names) * np.dtype(dtype).itemsize)
        sub = pdf[names]
        for lo in range(0, n, step):
            hi = min(lo + step, n)
            features[lo:hi] = sub.iloc[lo:hi].to_numpy(dtype=dtype)
        kind = "multi_cols"
    else:
        assert input_col is not None
        if input_col not in pdf.columns:
            raise ValueError(f"feature column {input_col!r} not in dataset")
        features, kind = _column_to_matrix(pdf[input_col], dtype)
        names = [input_col]

    if _sp is not None and _sp.issparse(features):
        if enable_sparse_data_optim is False:
            features = np.asarray(features.todense(), dtype=dtype)
    elif enable_sparse_data_optim is True:
        raise ValueError("enable_sparse_data_optim=True requires sparse vector input")

    def _scalar(colname: Optional[str], dt) -> Optional[np.ndarray]:
        if colname is None or colname == "":
            return None
        if colname not in pdf.columns:
            raise ValueError(f"column {colname!r} not in dataset")
        return pdf[colname].to_numpy(dtype=dt)

    return _record_ingest(ExtractedData(
        features=features,
        label=_scalar(label_col, dtype),
        weight=_scalar(weight_col, dtype),
        row_id=_scalar(id_col, np.int64),
        feature_kind=kind,
        feature_names=names,
    ), label_col=label_col, weight_col=weight_col, validate=validate)


def vectors_to_pandas_column(matrix: np.ndarray) -> list:
    """Dense 2-D block -> list of DenseVector for a vector-typed output column."""
    return [DenseVector(row) for row in np.asarray(matrix)]


def attach_column(dataset: Any, pdf_out, name: str, values) -> Any:
    """Append a column to the (pandas-normalized) dataset, preserving pandas type."""
    out = pdf_out.copy(deep=False)
    out[name] = list(values) if getattr(values, "ndim", 1) > 1 else values
    return out
