#
# ctypes surface over the in-tree C++ component (native/ — the reference's
# JNI loader analog, jvm/.../JniRAPIDSML.java:64-77: extract + System.load).
# Builds lazily with CMake on first use; all callers degrade gracefully when
# no toolchain is present (the JAX path never needs the native lib — it exists
# for native-stack parity: covariance accumulation, symmetric eig, signflip).
#
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_BUILD_DIR = os.path.join(_NATIVE_DIR, "build")
_LIB: Optional[ctypes.CDLL] = None
_LOAD_ERROR: Optional[str] = None


def _lib_path() -> str:
    return os.path.join(_BUILD_DIR, "libsrml_native.so")


def build(force: bool = False) -> str:
    """Build libsrml_native.so with CMake (reference jvm/native build step)."""
    if os.path.exists(_lib_path()) and not force:
        return _lib_path()
    os.makedirs(_BUILD_DIR, exist_ok=True)
    subprocess.run(
        ["cmake", "-DCMAKE_BUILD_TYPE=Release", ".."],
        cwd=_BUILD_DIR, check=True, capture_output=True,
    )
    subprocess.run(
        ["cmake", "--build", ".", "--parallel"],
        cwd=_BUILD_DIR, check=True, capture_output=True,
    )
    return _lib_path()


def load(auto_build: bool = True) -> ctypes.CDLL:
    """Load (building if needed) the native library; raises RuntimeError with
    the underlying cause when unavailable."""
    global _LIB, _LOAD_ERROR
    if _LIB is not None:
        return _LIB
    if _LOAD_ERROR is not None:
        raise RuntimeError(f"native library unavailable: {_LOAD_ERROR}")
    try:
        path = _lib_path()
        if not os.path.exists(path):
            if not auto_build:
                raise FileNotFoundError(path)
            build()
        lib = ctypes.CDLL(path)
        lib.srml_cov_accumulate.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double),
        ]
        lib.srml_weighted_mean.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64, ctypes.c_int64, ctypes.POINTER(ctypes.c_double),
        ]
        lib.srml_eigh_jacobi.restype = ctypes.c_int
        lib.srml_eigh_jacobi.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_double),
            ctypes.c_int, ctypes.c_double,
        ]
        lib.srml_signflip.argtypes = [
            ctypes.POINTER(ctypes.c_double), ctypes.c_int64, ctypes.c_int64,
        ]
        _LIB = lib
        return lib
    except Exception as e:  # record so later callers fail fast with the cause
        _LOAD_ERROR = str(e)
        raise RuntimeError(f"native library unavailable: {_LOAD_ERROR}") from e


def available() -> bool:
    try:
        load()
        return True
    except RuntimeError:
        return False


def _dptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


def cov_accumulate(x: np.ndarray, c: Optional[np.ndarray] = None) -> np.ndarray:
    """C += XᵀX (row-major blocked; rapidsml_jni dgemmCov analog)."""
    lib = load()
    x = np.ascontiguousarray(x, dtype=np.float64)
    n, d = x.shape
    if c is None:
        c = np.zeros((d, d), dtype=np.float64)
    else:
        c = np.ascontiguousarray(c, dtype=np.float64)
    lib.srml_cov_accumulate(_dptr(x), n, d, _dptr(c))
    return c


def weighted_mean(x: np.ndarray, w: Optional[np.ndarray] = None) -> np.ndarray:
    lib = load()
    x = np.ascontiguousarray(x, dtype=np.float64)
    n, d = x.shape
    out = np.zeros(d, dtype=np.float64)
    wp = _dptr(np.ascontiguousarray(w, dtype=np.float64)) if w is not None else None
    lib.srml_weighted_mean(_dptr(x), wp, n, d, _dptr(out))
    return out


def eigh(a: np.ndarray, max_sweeps: int = 60, tol: float = 1e-14) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric eigendecomposition (cyclic Jacobi): ascending eigenvalues,
    eigenvectors as COLUMNS (numpy.linalg.eigh convention; the reference's
    cuSOLVER eigDC analog, rapidsml_jni.cu:215-269)."""
    lib = load()
    a = np.ascontiguousarray(a, dtype=np.float64)
    d = a.shape[0]
    if a.shape != (d, d):
        raise ValueError("eigh expects a square matrix")
    evals = np.zeros(d, dtype=np.float64)
    evecs = np.zeros((d, d), dtype=np.float64)
    rc = lib.srml_eigh_jacobi(_dptr(a), d, _dptr(evals), _dptr(evecs), max_sweeps, tol)
    if rc < 0:
        raise RuntimeError("Jacobi eigensolver did not converge")
    return evals, evecs


def signflip(comps: np.ndarray) -> np.ndarray:
    """Row-wise sign canonicalization (rapidsml_jni.cu:35-61 semantics)."""
    lib = load()
    comps = np.ascontiguousarray(comps, dtype=np.float64)
    k, d = comps.shape
    lib.srml_signflip(_dptr(comps), k, d)
    return comps


def pca_from_cov(
    x: np.ndarray, k: int, w: Optional[np.ndarray] = None
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """End-to-end native PCA fit on host data: mean -> centered covariance ->
    Jacobi eig -> top-k sign-flipped components. Mirrors the Scala path
    RapidsRowMatrix.computePrincipalComponentsAndExplainedVariance
    (RapidsRowMatrix.scala:59-141). Returns (components [k, d], explained
    variance [k], mean [d])."""
    x = np.ascontiguousarray(x, dtype=np.float64)
    n, d = x.shape
    mean = weighted_mean(x, w)
    xc = x - mean[None, :]
    if w is not None:
        xc = xc * np.sqrt(np.asarray(w, dtype=np.float64))[:, None]
        denom = float(np.sum(w)) - 1.0
    else:
        denom = float(n) - 1.0
    cov = cov_accumulate(xc) / max(denom, 1.0)
    evals, evecs = eigh(cov)
    top = np.argsort(evals)[::-1][:k]
    comps = signflip(evecs[:, top].T.copy())
    var = evals[top]
    return comps, var, mean
