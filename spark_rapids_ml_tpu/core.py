#
# Core execution framework: everything shared by all algorithms.
#
# This is the TPU-native re-design of the reference's L5 (reference core.py, 1661
# LoC): `_CumlCaller`/`_CumlEstimator`/`_CumlModel`. The reference's shape —
# driver builds a barrier RDD of pandas UDF tasks, one per GPU, each task
# bootstraps NCCL and calls a cuML MG solver — collapses on TPU into a
# single-controller SPMD program: the features are laid out once as a row-sharded
# global `jax.Array` over a device `Mesh`, and the solver is a jitted function
# whose collectives (`psum` etc.) XLA lowers onto ICI. The estimator/model
# contracts, param flow, persistence format, fitMultiple single-pass semantics,
# and transform batching all mirror the reference 1:1 so the API stays drop-in.
#
# Reference call-stack parity (SURVEY.md §3.1): fit(df) -> _fit_internal ->
# _call_fit_func -> [extract cols (core.py:458-557) -> partition/pad
# (core.py:452-456) -> process-group context (core.py:768-774) ->
# per-algo fit closure (core.py:781)] -> _create_model (core.py:1040-1052).
#
from __future__ import annotations

import contextvars
import json
import os
import shutil
import threading
import time
from abc import abstractmethod
from collections import namedtuple
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np

from .data import ExtractedData, as_pandas, extract_dataset, vectors_to_pandas_column
from .params import Param, Params, _TpuParams
from .utils import get_logger, lockcheck


def _env_float(name: str, default: float) -> float:
    """Env-seeded float config value; a typo'd value falls back to the
    default instead of crashing package import (audit._capacity precedent)."""
    try:
        return float(os.environ.get(name) or default)
    except ValueError:
        return default


# Global framework configuration — the analog of the reference's Spark-conf tier
# (`spark.sql.execution.arrow.maxRecordsPerBatch`, `spark.rapids.ml.uvm.enabled`;
# reference core.py:660-665, clustering.py:775-779).
config: Dict[str, Any] = {
    "max_records_per_batch": 1 << 16,  # rows per transform batch (PER DEVICE on the mesh path)
    "broadcast_chunk_bytes": 8 << 30,  # 8GB broadcast chunking parity (clustering.py:1013-1091)
    # transform batches at or above this row count are row-sharded over the
    # whole mesh (model state replicated) instead of running on one device —
    # the reference's transform is parallel across all GPUs (core.py:1531-1635)
    "distributed_transform_min_rows": 1 << 15,
    # host-side ingest chunking: per-row feature columns are converted
    # column -> contiguous block (and CSR -> ELL) in row chunks of at most
    # this many bytes, so ingest temporaries stay bounded instead of scaling
    # with the dataset (the streaming analog of the reference's Arrow
    # maxRecordsPerBatch-bounded batch loop, reference core.py:698-760)
    "ingest_chunk_bytes": 128 << 20,
    # rows per tile of the shared distance/top-k core (ops/distance.py,
    # docs/performance.md "Tiled distance core"): the outer row-tile every
    # neighbor-family scan shares — kNN query tiles, kmeans_predict
    # assignment tiles, the kernel block planner's input. Bounds the live
    # [tile, k] reduction footprint on the fallback path and the per-tile
    # VMEM working set on the Pallas path.
    "distance_tile_rows": 4096,
    # --- fault-tolerant control plane (docs/robustness.md) ---------------
    # per-round rendezvous deadline: a round with ranks still missing raises
    # RendezvousTimeoutError (transient, retryable) when this elapses —
    # Spark's spark.barrier.sync.timeout analog
    "rendezvous_timeout_s": 300.0,
    # liveness-file cadence for FileRendezvous; a peer whose heartbeat goes
    # stale by 1.5x this raises RankFailedError on survivors, so a killed
    # rank surfaces within 2x the interval instead of the full round deadline
    "heartbeat_interval_s": 5.0,
    # success-path TpuContext teardown barrier bound: a peer that already
    # exited must not hang teardown — timing out here logs a warning only
    "teardown_timeout_s": 15.0,
    # retryable_stage policy: transient failures (rendezvous timeout,
    # distributed-init race — errors.is_transient) are retried up to this
    # many times with exponential backoff from this base, capped at
    # fit_retry_backoff_max_s (uncapped base * 2^N sleeps for minutes before
    # the final attempt of a high fit_max_retries budget)
    "fit_max_retries": 2,
    "fit_retry_backoff_s": 0.5,
    "fit_retry_backoff_max_s": 30.0,
    # --- elastic recovery (docs/robustness.md "Elastic recovery") ---------
    # solver-checkpoint cadence in inner iterations: at each boundary the
    # solver state is host-fetched so an interrupted fit resumes from the
    # last checkpoint instead of from scratch. 0 disables (default — no
    # extra host sync is ever added to an un-checkpointed fit).
    "checkpoint_every_iters": 0,
    # how many rank losses one fit may absorb through survivor re-meshing
    # (recovery epochs) before degrading to the typed RankFailedError
    "recovery_max_rank_losses": 1,
    # minimum membership window a reform round stays open, so a respawned
    # rank relaunched promptly after a kill can rejoin at the epoch boundary
    # (0 = close as soon as all known-live ranks have voted)
    "recovery_rejoin_grace_s": 0.0,
    # how many times a CrossValidator/TrainValidationSplit sweep may resume
    # after a mid-flight failure; the completion ledger (tuning.SweepLedger)
    # guarantees finished (fold, paramMap) fits are never redone
    "sweep_max_resumes": 1,
    # opt-in NaN/Inf scan over ingested feature/label/weight columns
    # (chunked under ingest_chunk_bytes); raises IngestValidationError
    # naming the column instead of feeding NaNs to a solver
    "validate_ingest": False,
    # --- memory safety (docs/robustness.md "Memory safety") ---------------
    # per-device HBM capacity override for the admission budgeter
    # (spark_rapids_ml_tpu/memory.py). None = use the device-reported
    # bytes_limit where the backend exposes it (TPU/GPU); CPU has none, so
    # fits stay unbudgeted there unless this is set.
    "hbm_budget_bytes": None,
    # fraction of the capacity RESERVED (not budgeted) for the transform
    # bucket ladder, compiled-program scratch, and allocator fragmentation:
    # the admission budget is capacity * (1 - this)
    "hbm_headroom_fraction": 0.1,
    # rows per out-of-core streaming chunk (the double-buffered host->HBM
    # pipeline's unit). 0 = auto: sized so two in-flight chunks + the solver
    # workspace fit the budget (floor 256 rows; 65536 when no capacity
    # information bounds it).
    "stream_chunk_rows": 0,
    # --- multi-fit execution engine (docs/performance.md) ----------------
    # XLA persistent compilation cache directory: compiled programs (the
    # transform bucket ladder, batched sweep solvers) survive process
    # restarts. Seeded from SRML_COMPILE_CACHE_DIR; None disables.
    "compilation_cache_dir": os.environ.get("SRML_COMPILE_CACHE_DIR") or None,
    # smallest rung of the transform bucket ladder: serving batches pad up a
    # geometric (x2) ladder of row counts starting here, so `predict`
    # compiles once per rung instead of once per distinct tail shape
    "transform_bucket_min_rows": 256,
    # max DeviceDatasets (HBM placements + pinned host datasets) a
    # device_dataset_scope retains at once; least-recently-used entries are
    # evicted beyond this, so a scope wrapped around a loop over FRESH
    # dataset objects cannot stack placements until HBM OOMs
    "device_dataset_cache_entries": 2,
    # --- multi-tenant fit scheduler (docs/scheduling.md) -----------------
    # preemptions one job may absorb before the scheduler demotes it to the
    # out-of-core streaming path (a floor-chunk footprint that packs into
    # almost any budget — degraded-mode service instead of starvation);
    # estimators without a streaming path become non-preemptible instead
    "sched_max_preemptions": 2,
    # co-admitted jobs running concurrently at most, regardless of how many
    # bin-pack into the ledger — bounds worker threads and per-job compile
    # pressure (a fairness/safety knob, docs/scheduling.md)
    "sched_max_concurrent": 4,
    # 2-D placement mode (docs/scheduling.md "2-D placement"): scheduler
    # claims name WHICH chips (contiguous first-fit runs over the pool) and
    # each job runs pinned to its claimed set via parallel.mesh.chip_scope,
    # so jobs of disjoint widths co-admit onto disjoint chip sets and run
    # concurrently instead of time-slicing the whole mesh. False keeps the
    # 1-D bytes-only book.
    "sched_chip_placement": False,
    # hierarchical mesh topology for parallel.mesh.build_mesh: None = flat
    # 1-D `rows` mesh; a dict like {"dcn": 2, "rows": 4} composes a DCN
    # (cross-process) axis with an ICI (in-process) axis — either axis may
    # be 0/absent to auto-derive from the process grouping
    "mesh_topology": None,
    # --- serving plane (docs/serving.md) ---------------------------------
    # how long the ScoringEngine holds a dispatched request open for
    # same-model coalescing (micro-batching up the bucket ladder): the
    # latency/throughput knob — 0 disables coalescing entirely
    "serve_coalesce_window_ms": 2.0,
    # row cap of one coalesced serving batch (and of a resident model's
    # PredictProgram bucket ladder); larger requests split across dispatches
    "serve_max_batch_rows": 8192,
    # model-load prewarm: every bucket-ladder rung up to this many rows is
    # compiled (through the persistent compile cache) AT LOAD TIME, so a
    # resident model's first query is compile-free; 0 disables prewarm
    "serve_prewarm_rows": 4096,
    # --- serving overload control (docs/serving.md "Overload &
    # backpressure") ------------------------------------------------------
    # server-side deadline applied to every submit() that does not pass its
    # own deadline_ms: an expired request NEVER dispatches (typed
    # RequestTimeoutError), and admission refuses a request whose deadline
    # the live queue-wait p99 predicts unmeetable (typed ServeOverloadError).
    # Monotonic-clock only. 0 disables the default deadline.
    "serve_default_deadline_ms": 30000.0,
    # bounded request queue: total rows queued in the ScoringEngine at most;
    # a submit that would exceed it is refused at admission instead of
    # growing an unbounded backlog
    "serve_max_queue_rows": 262144,
    # adaptive micro-batching: when True the coalesce window/row target
    # self-tune from the windowed arrival rate and queue-wait p99 (bounded
    # by the floor/ceiling below) — saturation grows batches instead of
    # queues. Uncongested traffic (queue-wait p99 at or under the static
    # window) behaves exactly like the static window, and
    # serve_coalesce_window_ms=0 still disables coalescing entirely.
    "serve_adaptive_batching": True,
    "serve_coalesce_window_floor_ms": 0.5,
    "serve_coalesce_window_ceiling_ms": 20.0,
    # backpressure ladder hysteresis: minimum dwell (seconds) between a
    # tenant's ladder transitions (throttle -> degrade -> shed and every
    # restore step), so a burn flap cannot flap the ladder
    "serve_overload_hold_s": 30.0,
    # per-tenant token-bucket rate while a tenant is at the throttle rung,
    # in rows/second; 0 = auto (half the tenant's recent admitted row rate)
    "serve_throttle_rows_per_s": 0.0,
    # opt-in degraded serving rung: a serve dtype (e.g. "bf16") the registry
    # builds as a SECOND resident program (its bytes honestly admitted
    # against the HBM budget) for models whose `_serve_dtypes` allow it —
    # the backpressure ladder routes a burning tenant's traffic there before
    # shedding. None disables the rung (the ladder skips degrade).
    "serve_degraded_dtype": None,
    # --- distributed diagnostics (docs/observability.md) -----------------
    # directory for flight-recorder dumps (`flightrec_rank_<r>.jsonl`) on
    # SrmlError / abort publication; seeded from SRML_FLIGHTREC_DIR. None ->
    # exception tails still attach, but no dump files are written.
    "flightrec_dir": os.environ.get("SRML_FLIGHTREC_DIR") or None,
    # --- live ops plane (docs/observability.md "Ops plane") ---------------
    # rolling-window ring geometry for the telemetry registry: every counter
    # gets rate() and every histogram gets window_quantile() over the most
    # recent bucket_seconds x bucket_count horizon (default 10s x 18 = 3min).
    # Resolved when a ring is first written — change before recording, or
    # call telemetry.registry().reset() to apply.
    "metrics_bucket_seconds": 10.0,
    "metrics_bucket_count": 18,
    # declarative SLO specs evaluated by multi-window burn rate
    # (ops_plane.slo; grammar in docs/observability.md "SLO specs"): a list
    # of dicts naming a latency histogram / error-rate counter pair / gauge
    # ceiling plus thresholds. None or [] disables the monitors entirely.
    "slo": None,
    # directory for rotating ops-plane snapshots (`ops_snapshot.json` +
    # bounded .1/.2/... generations, ops_plane.export.write_snapshot) — the
    # headless-run analog of the SRML_METRICS_PORT scrape surface; seeded
    # from SRML_OPS_SNAPSHOT_DIR. None -> no files.
    "ops_snapshot_dir": os.environ.get("SRML_OPS_SNAPSHOT_DIR") or None,
    # --- runtime lock-order sanitizer (docs/robustness.md "Threading
    # model") -------------------------------------------------------------
    # hold duration (ms) above which the SRML_LOCKCHECK=1 sanitizer records
    # a `lockcheck.long_hold` violation for a framework lock — the runtime
    # face of the static blocking-under-lock rule. Seeded from
    # SRML_LOCKCHECK_LONG_HOLD_MS; only read while the sanitizer is on. A
    # typo'd value falls back to the default — it must not crash package
    # import (utils.lockcheck.long_hold_threshold_s guards the same way).
    "lockcheck_long_hold_ms": _env_float("SRML_LOCKCHECK_LONG_HOLD_MS", 500.0),
    # --- mixed-precision solver contract (docs/performance.md
    # "Mixed-precision solvers") ------------------------------------------
    # default precision for the SANCTIONED hot contractions of every solver
    # fit: "f32" (default) keeps all fit arithmetic at the ambient input
    # precision; "bf16" routes the per-solver hot paths (k-means
    # assign+accumulate, GLM X·β / Xᵀr matvecs, linear/PCA sufficient-stat
    # einsums) through bf16 inputs with f32 accumulators. Convergence
    # scalars, L-BFGS state, and all REPORTED metrics stay full precision in
    # both modes. Per-estimator override via the `solver_precision` solver
    # param; seeded from SRML_SOLVER_PRECISION.
    "solver_precision": os.environ.get("SRML_SOLVER_PRECISION") or "f32",
    # --- measured kernel autotuner (ops/autotune.py) ---------------------
    # on first TPU contact per (shape-class, dtype, fast-flag) the Pallas
    # distance-core block planner times a small (block_rows, block_k)
    # candidate grid on-device and persists the winner as JSON beside the
    # XLA compile cache (compilation_cache_dir). SRML_AUTOTUNE=0 disables;
    # off-TPU (or cold-start) the static half-VMEM heuristic is used, so
    # CPU/CI behavior is unchanged.
    "autotune_enabled": os.environ.get("SRML_AUTOTUNE", "1")
    not in ("", "0", "false", "off"),
    # timing repeats per candidate tiling when the autotuner measures; the
    # minimum over repeats is scored (robust to one-off scheduling noise)
    "autotune_repeats": 3,
    # --- efficiency attribution plane (ops_plane/efficiency.py,
    # docs/observability.md "Efficiency plane") ---------------------------
    # per-device peak FLOP/s for the roofline/MFU gauges — the peak-spec
    # grammar is a number with an optional K/M/G/T/P suffix ("14T",
    # "275e12"). Unset (default) = the `efficiency.mfu` gauges are OMITTED,
    # never guessed from the device model. Seeded from
    # SRML_DEVICE_PEAK_FLOPS.
    "device_peak_flops": os.environ.get("SRML_DEVICE_PEAK_FLOPS") or None,
    # --- fleet observability plane (ops_plane/fleet.py,
    # docs/observability.md "Fleet plane") --------------------------------
    # minimum seconds between live ops rounds (the throttled cross-rank
    # window exchange piggybacked on the rendezvous control plane). None
    # (default) = one metrics bucket width (metrics_bucket_seconds) — the
    # finest cadence at which a new exchange can carry new window data.
    "fleet_ops_round_seconds": None,
    # consecutive ops rounds a rank must be the slowest round-exiter (by at
    # least fleet_straggler_min_lag_s) before the straggler detector fires a
    # flight-recorder event + audit entry naming it
    "fleet_straggler_windows": 3,
    # lag floor (seconds behind the fastest rank's round exit) below which a
    # rank is never counted as straggling — jitter under this is noise
    "fleet_straggler_min_lag_s": 0.05,
    # per-rank ops snapshots older than this (by their meta.t header) are
    # dropped from the offline cluster merge as stale dead-rank data and
    # named in the `opsreport --cluster` partial verdict
    "fleet_stale_snapshot_s": 600.0,
}


def resolve_solver_precision(params: Optional[Dict[str, Any]] = None) -> str:
    """Effective solver precision for ONE fit: the estimator's
    ``solver_precision`` solver-param when set (per-estimator override),
    else ``config["solver_precision"]``. Returns "f32" or "bf16"; anything
    else raises ValueError naming the knob. The choice is counted
    (`fit.precision_f32` / `fit.precision_bf16`) so the BENCH/ops artifacts
    can audit which precision every fit actually ran at."""
    value = params.get("solver_precision") if params else None
    if value is None:
        value = config.get("solver_precision") or "f32"
    value = str(value).lower()
    if value not in ("f32", "bf16"):
        raise ValueError(
            f"solver_precision must be 'f32' or 'bf16', got {value!r}"
        )
    from . import telemetry

    if telemetry.enabled():
        telemetry.registry().inc(
            "fit.precision_bf16" if value == "bf16" else "fit.precision_f32"
        )
    return value

def evaluator_label_column(params_obj: Any, evaluator: Any) -> str:
    """The label column an evaluator scores against: its own ``labelCol``
    when it defines one, else the estimator/model's. The ONE resolution
    shared by the fused transform-evaluate paths and the tuning layer's
    held-out scoring, so they cannot drift."""
    if hasattr(evaluator, "hasParam") and evaluator.hasParam("labelCol"):
        return evaluator.getOrDefault("labelCol")
    return params_obj.getOrDefault("labelCol")


# Output-column naming contract shared by all predictive models
# (reference core.py:146-160 `pred` namedtuple).
pred = namedtuple("pred", ("prediction", "probability", "raw_prediction", "model_index"))(
    "prediction", "probability", "rawPrediction", "model_index"
)

# Internal column aliases used during pre-processing (reference core.py:123-144).
alias = namedtuple("alias", ("data", "label", "weight", "row_number"))(
    "tpu_values", "tpu_label", "tpu_weight", "unique_id"
)


@dataclass
class StreamPlan:
    """Out-of-core execution plan attached to a demoted fit's `FitInputs`
    (docs/robustness.md "Memory safety"): the host-retained extracted blocks
    plus the ADMITTED chunk size. Streaming solver drivers (ops/streaming.py)
    cut row chunks from `extracted`, validate them per block when
    ``config["validate_ingest"]`` asked for it, and feed them through the
    double-buffered host->HBM pipeline. Mutable bookkeeping: `validated_rows`
    (per-block validation watermark — later passes over scanned rows are
    free) and the once-per-fit CSR->ELL block cache."""

    extracted: Any  # host ExtractedData (dense np block or scipy CSR)
    chunk_rows: int
    validate: bool = False
    admission: Any = None  # the memory.AdmissionDecision that demoted the fit
    validated_rows: int = 0
    ell_blocks: Any = None  # once-per-fit CSR->ELL host blocks (global k_max)
    ell_k_max: int = 0


@dataclass
class FitInputs:
    """Device-resident inputs handed to every algorithm's fit function.

    The analog of the reference MG calling convention `(parts, m, n,
    parts_rank_size, rank)` + raft handle (reference feature.py:234-241): here the
    "handle" is the mesh, and the ragged partition layout is replaced by
    pad-to-equal row blocks with zero weights on padding (SURVEY.md §7 hard parts).
    """

    mesh: Any  # jax.sharding.Mesh
    X: Any  # row-sharded jax.Array [n_pad, d], or None when sparse
    y: Any  # row-sharded jax.Array [n_pad] or None
    w: Any  # row-sharded jax.Array [n_pad]; 0.0 on padding rows
    n_valid: int  # GLOBAL valid row count (sum over processes under SPMD)
    n_cols: int
    desc: Any  # PartitionDescriptor
    dtype: Any
    X_sparse: Any = None  # host scipy CSR when the sparse path is active
    ctx: Any = None  # the TpuContext the fit runs under (rendezvous access)
    local_rows_target: Any = None  # per-process padded local rows (SPMD mode)
    # host-side boolean over the VALID rows naming which participate in this
    # fit (None = all). Set by `with_row_mask`; fit funcs that derive host
    # statistics from raw columns (label class sets) must respect it.
    host_mask: Any = None
    # out-of-core execution plan (a demoted fit): X is NOT placed — y/w are
    # HOST arrays and solvers stream row chunks via ops/streaming.py
    stream: Optional["StreamPlan"] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    def put_rows(self, host_rows: np.ndarray, weights: Optional[np.ndarray] = None) -> Any:
        """Lay an additional per-row host array out on the mesh with the SAME
        row layout/padding as X (labels, per-row stats, ...). Under SPMD every
        process passes its local slice; padding matches X's so row i of the
        result still corresponds to row i of X."""
        from .parallel import make_global_rows

        arr, _, _ = make_global_rows(
            self.mesh, host_rows, weights=weights, local_rows_target=self.local_rows_target
        )
        return arr

    def allgather_host(self, payload: str) -> List[str]:
        """Control-plane allgather of small strings across ranks (host-side
        statistics merging: class sets, bin edges, init centers). Identity in
        single-controller mode."""
        if self.ctx is not None and self.ctx.is_spmd:
            return self.ctx.rendezvous.allgather(payload)
        return [payload]

    def ell_rows(self):
        """Device-resident padded-ELL form of `X_sparse` (ops/sparse.py),
        laid out with the SAME row layout/padding as the dense path:
        returns (values, indices) row-sharded jax.Arrays. Under SPMD the pad
        width k_max is the rendezvous-agreed GLOBAL widest row so all ranks
        trace identical shapes.

        MEMOIZED on `extra` (which `with_row_mask`'s shallow replace shares
        across fold variants): the ELL tensors depend only on the data,
        dtype, and layout — never on weights or hyperparameters — so a CV
        grid over a sparse dataset converts and places them ONCE, not once
        per solve (the sparse half of the one-placement contract)."""
        cached = self.extra.get("_ell_rows")
        if cached is not None:
            return cached
        from .ops.sparse import csr_to_ell

        assert self.X_sparse is not None, "ell_rows() requires a sparse fit input"
        local_kmax = (
            int(np.diff(self.X_sparse.indptr).max()) if self.X_sparse.shape[0] else 0
        )
        k_max = max(int(g) for g in self.allgather_host(str(local_kmax)))
        idx_h, val_h, _ = csr_to_ell(self.X_sparse, k_max=k_max, dtype=self.dtype)
        out = (self.put_rows(val_h), self.put_rows(idx_h))
        self.extra["_ell_rows"] = out
        return out

    def with_row_mask(self, mask: np.ndarray) -> "FitInputs":
        """These inputs with the rows where ``mask == 0`` neutralized:
        ``w -> w * mask``. The solvers already treat ``w == 0`` rows as
        padding, so a masked fit over the FULL placed dataset computes
        exactly the fit over the mask's rows — this is how CrossValidator
        realizes a fold without re-ingesting or re-laying-out anything
        (one HBM placement serves every fold). The placed X/y are shared
        untouched; only the tiny weight vector is re-derived per fold.

        Under multi-process SPMD the mask names THIS RANK's local valid
        rows (`n_valid` is the global sum): each rank masks its own slice
        and `put_rows` pads it out to the rendezvous-agreed local target,
        so one fold is the union of every rank's local train rows."""
        import dataclasses

        m = np.ascontiguousarray(np.asarray(mask), dtype=self.dtype)
        spmd_local = self.local_rows_target is not None and m.shape[0] != self.n_valid
        if spmd_local:
            if m.shape[0] > int(self.local_rows_target):
                raise ValueError(
                    f"row mask has {m.shape[0]} entries for a local row "
                    f"target of {int(self.local_rows_target)}"
                )
        elif m.shape[0] != self.n_valid:
            raise ValueError(
                f"row mask has {m.shape[0]} entries for {self.n_valid} rows"
            )
        if self.X_sparse is not None or self.stream is not None:
            # sparse and streaming paths carry host weights
            w_masked = np.asarray(self.w) * m
        else:
            w_masked = self.w * self.put_rows(m)  # padding rows stay 0
        return dataclasses.replace(self, w=w_masked, host_mask=m > 0)

    def allgather_array(self, arr: np.ndarray) -> np.ndarray:
        """Control-plane allgather of a host numpy block, concatenated in rank
        order along axis 0. Identity in single-controller mode. Used to merge
        host-side per-rank samples (KMeans init candidates, RF quantile-sketch
        rows) — the reference's BarrierTaskContext.allGather of base64 payloads
        (e.g. tree.py:343, classification.py:1006-1012)."""
        if self.ctx is None or not self.ctx.is_spmd:
            return arr
        from .parallel.context import allgather_ndarray

        return np.concatenate(
            allgather_ndarray(self.ctx.rendezvous, arr), axis=0
        )


def retryable_stage(
    fn: Callable[[int], Any],
    *,
    stage: str,
    rendezvous: Any = None,
    logger: Any = None,
    max_retries: Optional[int] = None,
    backoff_s: Optional[float] = None,
) -> Any:
    """Run ``fn(attempt)`` with bounded retries on TRANSIENT failures — the
    in-process analog of Spark's lineage-based stage re-execution (the crash
    recovery the reference inherits for free; Zaharia et al., NSDI 2012).

    Transient means `errors.is_transient`: rendezvous round timeouts (which
    fire symmetrically, so every SPMD rank unwinds and re-enters together)
    and the distributed-init race. Permanent failures — RankFailedError (a
    peer is dead), SolverDivergedError, user errors — propagate immediately.

    Before each retry: exponential backoff from ``config["fit_retry_backoff_s"]``
    (attempt N sleeps base * 2^(N-1), capped at
    ``config["fit_retry_backoff_max_s"]``), and `rendezvous.begin_epoch(attempt)`
    re-namespaces the control plane so the retry never reads the failed
    attempt's stale rounds. Every retry increments the ``fit.retries``
    telemetry counter, which lands in ``model._fit_metrics`` and the bench
    snapshot. The chaos hook (`parallel.chaos.maybe_fail_stage`) runs at the
    top of every attempt so fault plans can inject the transient path.

    A `checkpoint.CheckpointStore` is active for all attempts (adopting the
    enclosing `recoverable_stage`'s store when present): solvers that
    checkpoint (``config["checkpoint_every_iters"]``) resume a transient
    retry from the last checkpoint instead of from scratch."""
    from . import checkpoint as _checkpoint
    from . import diagnostics, telemetry
    from .errors import is_transient
    from .parallel import chaos

    if max_retries is None:
        max_retries = int(config.get("fit_max_retries", 2))
    if backoff_s is None:
        backoff_s = float(config.get("fit_retry_backoff_s", 0.5))
    backoff_max_s = float(config.get("fit_retry_backoff_max_s", 30.0))
    if logger is None:
        logger = get_logger("retryable_stage")
    with _checkpoint.ensure_scope():
        for attempt in range(max_retries + 1):
            try:
                chaos.maybe_fail_stage(stage, attempt)
                return fn(attempt)
            except Exception as e:
                if not is_transient(e) or attempt >= max_retries:
                    raise
                telemetry.registry().inc("fit.retries")
                diagnostics.record_event(
                    "retry", stage=stage, attempt=attempt + 1,
                    error=type(e).__name__,
                )
                sleep_s = min(backoff_s * (2 ** attempt), backoff_max_s)
                logger.warning(
                    "stage %s attempt %d/%d failed transiently (%s: %s); "
                    "retrying in %.2fs",
                    stage, attempt + 1, max_retries + 1, type(e).__name__, e, sleep_s,
                )
                time.sleep(sleep_s)  # sleep-ok: capped retry backoff (the one backoff owner)
                if rendezvous is not None:
                    rendezvous.begin_epoch(attempt + 1)
    raise AssertionError("unreachable")  # pragma: no cover


def recoverable_stage(
    fn: Callable[[int], Any],
    *,
    stage: str,
    ctx: Any = None,
    rendezvous: Any = None,
    on_recover: Optional[Callable[[Any, int, set], None]] = None,
    logger: Any = None,
    max_rank_losses: Optional[int] = None,
    max_retries: Optional[int] = None,
    backoff_s: Optional[float] = None,
) -> Any:
    """Elastic outer layer over `retryable_stage`: grow abort-and-retry into
    survivor re-meshing (docs/robustness.md "Elastic recovery").

    Transient failures retry as before. A `RankFailedError` — previously
    always terminal — now opens a RECOVERY EPOCH when the rendezvous
    substrate supports membership reform (so does an exhausted
    `RendezvousTimeoutError` that names missing ranks: a peer dead before
    first contact never heartbeats, so it can only surface as a timeout;
    the reform is evidence-based — no dead hint — so a merely-slow rank
    that votes late is re-admitted): survivors agree on the live rank
    set (`rendezvous.reform`, which also admits a respawned rank rejoining
    at the epoch boundary), the context adopts the reformed group
    (`ctx.adopt_reform`: new rank/nranks + the mesh rebuilt over survivors),
    and the stage re-enters — solvers resume from the last checkpoint in the
    shared `CheckpointStore` rather than from scratch. Bounded by
    ``config["recovery_max_rank_losses"]``; exhaustion (or a substrate
    without reform) degrades to today's typed failure.

    `on_recover(new_rendezvous, generation, dead_original_ranks)` lets
    context-free callers (the chaos harness) swap their rendezvous handle.
    Recovery epochs are counted (``fit.recoveries`` / ``recovery.epochs`` /
    ``recovery.rank_losses``) and flight-recorded, and the ring is dumped
    after each successful reform so post-mortems show the epoch."""
    from . import checkpoint as _checkpoint
    from . import diagnostics, telemetry
    from .errors import RankFailedError, RendezvousTimeoutError

    if rendezvous is None and ctx is not None:
        rendezvous = getattr(ctx, "rendezvous", None)
    if max_rank_losses is None:
        max_rank_losses = int(config.get("recovery_max_rank_losses", 1))
    if logger is None:
        logger = get_logger("recoverable_stage")
    losses = 0
    with _checkpoint.ensure_scope():
        while True:  # blocking-ok: every epoch charges the recovery budget; exhaustion raises
            try:
                return retryable_stage(
                    fn, stage=stage, rendezvous=rendezvous, logger=logger,
                    max_retries=max_retries, backoff_s=backoff_s,
                )
            except (RankFailedError, RendezvousTimeoutError) as e:
                if isinstance(e, RendezvousTimeoutError) and not getattr(
                    e, "missing_ranks", None
                ):
                    # a timeout naming NO missing ranks carries no liveness
                    # evidence to reform around (e.g. a desync, a chaos
                    # `fail` injection) — that stays retryable_stage's
                    # territory, and it already exhausted its budget
                    raise
                if (
                    rendezvous is None
                    or not getattr(rendezvous, "can_reform", False)
                    or losses >= max_rank_losses
                ):
                    # stamp how far recovery got before degrading to the
                    # typed failure (0 = never opened an epoch), so callers
                    # and post-mortems distinguish "unreformable substrate"
                    # from "budget exhausted"
                    e.recovery_exhausted = losses > 0
                    e.recovery_generations = losses
                    raise
                live = list(getattr(rendezvous, "live_ranks", range(rendezvous.nranks)))
                dead = set()
                failed_rank = getattr(e, "failed_rank", None)
                if (
                    isinstance(e, RankFailedError)
                    and failed_rank is not None
                    and 0 <= failed_rank < len(live)
                ):
                    dead.add(live[failed_rank])
                # an exhausted TIMEOUT (a peer dead before it ever made
                # contact — no abort file, no heartbeat file to go stale)
                # seeds NO dead hint: the reform round's own evidence
                # (votes, abort files, heartbeat staleness) decides who is
                # gone, so a merely-slow rank that votes late is re-admitted
                # instead of excluded on circumstantial missing_ranks
                generation = int(getattr(rendezvous, "reform_generation", 0)) + 1
                reg = telemetry.registry()
                reg.inc("fit.recoveries")
                reg.inc("recovery.epochs")
                diagnostics.record_event(
                    "recovery_epoch_begin", stage=stage, generation=generation,
                    failed_rank=failed_rank,
                    dead_ranks=sorted(dead),
                )
                logger.warning(
                    "stage %s: rank failure (%s) — entering recovery epoch %d "
                    "over the survivor set", stage, e, generation,
                )
                new_rdv = rendezvous.reform(dead_ranks=dead, generation=generation)  # spmd-ok: recovery rendezvous — every survivor observes the same failure (heartbeat/abort scan) and enters reform, which carries its own deadline
                lost = len(live) - len(getattr(new_rdv, "live_ranks", range(new_rdv.nranks)))
                losses += max(1, lost)
                reg.inc("recovery.rank_losses", max(1, lost))
                if ctx is not None and hasattr(ctx, "adopt_reform"):
                    ctx.adopt_reform(new_rdv)
                if on_recover is not None:
                    on_recover(new_rdv, generation, dead)
                rendezvous = new_rdv
                diagnostics.record_event(
                    "recovery_epoch", stage=stage, generation=generation,
                    survivors=list(getattr(new_rdv, "live_ranks", range(new_rdv.nranks))),
                )
                # dump the ring so the post-mortem timeline NAMES the epoch
                # even when the fit then completes cleanly
                diagnostics.flight_recorder().dump(
                    reason=f"recovery epoch {generation}"
                )


# ---------------------------------------------------------------------------
# DeviceDataset: one ingest + layout, many fits (docs/performance.md
# "Multi-fit engine"). The reference's fitMultiple already reuses the placed
# data WITHIN one fit call (core.py:877-911); DeviceDataset extends that
# across fit calls — CV folds, sweep re-fits, and the best-model refit all
# hit the same HBM placement.
# ---------------------------------------------------------------------------


@dataclass
class DeviceDataset:
    """A dataset after ingest + layout, resident in HBM and reusable across
    fits. `key` is the cache key: (dataset identity fingerprint, extraction
    columns, dtype, mesh shape) — see `_TpuCaller._device_dataset_key`.
    `extracted` keeps the host-side blocks (features/label) so held-out
    scoring can slice rows without a pandas round-trip. `source` pins the
    ORIGINAL dataset object for the entry's lifetime: the fingerprint is
    `id()`-based, and without a strong reference CPython could recycle a
    garbage-collected dataset's id onto a new object of the same shape —
    a silent false cache hit training on the wrong data."""

    key: Optional[tuple]
    extracted: ExtractedData
    inputs: FitInputs
    source: Any = None
    # the memory.AdmissionDecision that admitted this placement — re-stamped
    # on fits served from the scope cache, so every fit's model carries its
    # verdict, not just the cache-miss one
    admission: Any = None


class DeviceDatasetScope:
    """Caching scope for DeviceDatasets. Fits inside the scope reuse a
    placed dataset when the key matches; the outermost scope exit drops the
    cache (releasing the HBM references). `last` is the dataset most
    recently built or reused — the tuning layer reads its host blocks for
    held-out scoring."""

    __slots__ = ("cache", "lock", "last")

    def __init__(self) -> None:
        self.cache: Dict[tuple, DeviceDataset] = {}  # guarded-by: lock
        self.lock = lockcheck.make_lock("core.DeviceDatasetScope.lock")
        self.last: Optional[DeviceDataset] = None


# Context-local (NOT process-global): concurrent scopes on different threads
# must neither share a cache nor clobber each other's enter/exit bookkeeping
# — with a bare global, interleaved exits across threads could resurrect an
# already-cleared scope with no owner left to release its HBM references.
# Threads spawned inside a scope start from a fresh context and simply do not
# see it (their fits ingest normally — correct, just uncached).
_DDS_SCOPE: "contextvars.ContextVar[Optional[DeviceDatasetScope]]" = contextvars.ContextVar(
    "srml_device_dataset_scope", default=None
)


def device_dataset_scope():
    """Context manager enabling DeviceDataset reuse for its dynamic extent.

    >>> with core.device_dataset_scope():
    ...     est.fit(df)            # ingest + layout + solve
    ...     est.copy(pm).fit(df)   # SAME placement, one more solve

    Nested scopes share the outermost cache; the scope is context-local, so
    fits running on OTHER threads neither see nor disturb it. Caching is
    identity-fingerprint based (cheap — the data is never hashed), so
    mutating the same dataset object in place between fits inside one scope
    is not detected; pass a new object instead."""
    import contextlib

    @contextlib.contextmanager
    def _scope():
        outer = _DDS_SCOPE.get()
        scope = outer if outer is not None else DeviceDatasetScope()
        token = _DDS_SCOPE.set(scope)
        try:
            yield scope
        finally:
            _DDS_SCOPE.reset(token)
            if outer is None:
                with scope.lock:
                    scope.cache.clear()  # free the HBM references

    return _scope()


# A fit function maps (inputs, solver_params) -> model-attribute dict.
FitFunc = Callable[[FitInputs, Dict[str, Any]], Dict[str, Any]]
# A transform triple: (construct_state, predict(state, X_batch), optional evaluate)
# mirroring the reference's (construct, transform, evaluate) closures
# (reference core.py:1434-1488).
TransformFuncs = Tuple[Callable[[], Any], Callable[[Any, np.ndarray], Any], Optional[Callable]]


class _TpuCommon(_TpuParams):
    """Input pre-processing shared by estimators (fit side) and models
    (transform side) — reference core.py:458-557 and 1205-1328 respectively."""

    _supports_sparse_input: bool = False
    _supervised: bool = False
    _use_weight_col: bool = True
    # Per-solver MXU precision policy (see parallel/mesh.py dtype_scope):
    # "float32" unless the solver's numeric contract tolerates fewer passes.
    _matmul_precision: str = "float32"

    def _pre_process_data(
        self, dataset: Any, for_fit: bool = True, defer_validation: bool = False
    ) -> ExtractedData:
        """Column selection + dense/CSR extraction (reference core.py:458-557).

        ``defer_validation=True`` skips the eager opt-in NaN/Inf scan — the
        fit driver must run it itself (`data.run_deferred_validation`): full
        scan before a RESIDENT layout, per row-block on the STREAMING path
        (where re-materializing the dataset just to validate it would defeat
        the memory budget)."""
        input_col, input_cols = self._get_input_columns()
        label_col = None
        if for_fit and self._supervised:
            label_col = self.getOrDefault("labelCol")
        weight_col = None
        if (
            for_fit
            and self._use_weight_col
            and self.hasParam("weightCol")
            and self.isDefined("weightCol")
        ):
            weight_col = self.getOrDefault("weightCol")
        id_col = None
        if self.hasParam("idCol") and self.isDefined("idCol"):
            id_col = self.getOrDefault("idCol")
        sparse_optim = (
            self.getOrDefault("enable_sparse_data_optim")
            if self.hasParam("enable_sparse_data_optim")
            else None
        )
        if sparse_optim is None and not self._supports_sparse_input:
            sparse_optim = False  # densify for algorithms without a CSR path
        extracted = extract_dataset(
            dataset,
            input_col=input_col,
            input_cols=input_cols,
            label_col=label_col,
            weight_col=weight_col,
            id_col=id_col,
            float32_inputs=self._float32_inputs,
            enable_sparse_data_optim=sparse_optim,
            validate=not defer_validation,
        )
        if for_fit and extracted.n_rows == 0:
            # reference raises the same way when a rank gets no rows (core.py:762-765)
            raise RuntimeError("Dataset is empty — cannot fit")
        return extracted


class _TpuCaller(_TpuCommon):
    """Shared fit-orchestration machinery (reference `_CumlCaller`, core.py:430-806)."""

    # Whether this estimator's fit function is correct under multi-process SPMD
    # (all host-side statistics either rendezvous-merged or absent). Estimators
    # flip this as they are proven by the multiprocess test harness.
    _supports_multiprocess: bool = False

    # Whether this estimator's fit function can run OUT-OF-CORE (an
    # inputs.stream plan routed to ops/streaming.py). Estimators whose solver
    # state is accumulable over row chunks (linear/PCA sufficient stats,
    # logistic full-batch gradients, k-means center sums) flip this; for the
    # rest an over-budget fit raises HbmBudgetError instead of demoting.
    _supports_streaming_fit: bool = False

    # the memory.AdmissionDecision of the most recent fit attempt (stamped
    # onto model._fit_metrics by _call_fit_func)
    _last_admission: Any = None

    # this fit's live claim in the shared HBM ledger (scheduler.HbmLedger,
    # docs/scheduling.md): one reservation spanning admission -> fit end,
    # swapped on every re-admission (retry/recovery/OOM-demotion) and
    # released in _call_fit_func's finally. None inside a scheduler job
    # (the job's own reservation is resized instead) and between fits.
    _fit_reservation: Any = None

    # portable warm-start payload for the NEXT fit call (set by
    # _TpuEstimator.fit(..., warm_start_from=...), consumed by the
    # per-estimator fit closures, cleared in fit's finally)
    _warm_start: Any = None

    def _adopt_reservation(self, reservation: Any) -> None:
        """Swap this fit's ledger claim: release the previous one (a retry's
        or a prior fit's leftover — idempotent) and hold the new. Decisions
        hand their reservation over here so the SHARED AdmissionDecision
        objects cached on DeviceDatasets never carry a live claim."""
        from .scheduler.ledger import global_ledger

        old = self._fit_reservation
        if old is not None:
            global_ledger().release(old)
        self._fit_reservation = reservation

    def _solver_workspace_terms(
        self, rows_per_device: int, n_cols: int, params: Dict[str, Any], itemsize: int
    ) -> Dict[str, int]:
        """Per-solver HBM workspace estimate hook for the admission budgeter
        (spark_rapids_ml_tpu/memory.py): named byte terms BEYOND the data
        placement — gram/covariance blocks, GLM logits + L-BFGS history,
        k-means tile buffers. Per device; {} (default) = no modeled
        workspace. Formulas are pinned by tests/test_memory.py."""
        return {}

    def _solver_flop_estimate(
        self, n_rows: int, n_cols: int
    ) -> Optional[float]:
        """Analytic FLOP estimate for ONE solve of this estimator — the
        `_solver_workspace_terms` sibling feeding the roofline/MFU gauges
        (ops_plane/efficiency.py): achieved fraction of the configured
        `config["device_peak_flops"]` peak. None (default) = no model; the
        MFU gauge is simply omitted for this estimator."""
        return None

    def _build_fit_inputs(self, extracted: ExtractedData, ctx: Any) -> FitInputs:
        """Lay the host blocks out on the mesh (pad-and-mask; SURVEY.md §7).

        Under multi-process SPMD (`ctx.is_spmd`) `extracted` is this PROCESS's
        local row block: the global layout is agreed through the rendezvous
        (PartitionDescriptor allgather — the reference's utils.py:192-210) and
        every process pads its block to the common per-process size before
        global-array assembly.
        """
        import jax

        from .parallel import PartitionDescriptor, make_global_rows

        mesh = ctx.mesh
        n_dev = mesh.devices.size
        dtype = np.float32 if self._float32_inputs else np.float64
        spmd = ctx.is_spmd

        local_rows_target = None
        if spmd:
            desc = PartitionDescriptor.build(
                [extracted.n_rows], extracted.n_cols, rank=ctx.rank, rendezvous=ctx.rendezvous
            )
            n_local_dev = jax.local_device_count()
            max_rows = max(r for _, r in desc.parts_rank_size)
            local_rows_target = -(-max_rows // n_local_dev) * n_local_dev
        else:
            desc = PartitionDescriptor.build(
                [extracted.n_rows // n_dev + (1 if i < extracted.n_rows % n_dev else 0) for i in range(n_dev)],
                extracted.n_cols,
            )

        weights = extracted.weight
        if extracted.is_sparse:
            X = None
            X_sparse = extracted.features
            import numpy as _np

            w_np = weights if weights is not None else _np.ones(extracted.n_rows, dtype=dtype)
            w = w_np
            y = extracted.label
            return FitInputs(
                mesh=mesh, X=None, y=y, w=w, n_valid=desc.m, n_cols=extracted.n_cols,
                desc=desc, dtype=dtype, X_sparse=X_sparse, ctx=ctx,
                local_rows_target=local_rows_target,
            )

        X, w, _ = make_global_rows(
            mesh, extracted.features.astype(dtype, copy=False), weights=weights,
            local_rows_target=local_rows_target,
        )
        y = None
        if extracted.label is not None:
            y, _, _ = make_global_rows(
                mesh, extracted.label.astype(dtype, copy=False),
                local_rows_target=local_rows_target,
            )
        return FitInputs(
            mesh=mesh, X=X, y=y, w=w, n_valid=desc.m, n_cols=extracted.n_cols,
            desc=desc, dtype=dtype, ctx=ctx, local_rows_target=local_rows_target,
        )

    @abstractmethod
    def _get_tpu_fit_func(self, extracted: ExtractedData) -> FitFunc:
        """Per-algorithm fit closure factory (reference `_get_cuml_fit_func`)."""
        raise NotImplementedError

    def _get_tpu_batched_fit_func(
        self, extracted: ExtractedData
    ) -> Optional[Callable[[FitInputs, List[Dict[str, Any]]], Optional[List[Dict[str, Any]]]]]:
        """Optional batched-sweep closure: ``f(inputs, param_sets)`` solves a
        whole hyperparameter group in ONE compiled program and returns one
        attribute dict per set — or None to decline at runtime (the caller
        falls back to the sequential loop). Estimators whose solvers take
        the swept hyperparameters as traced scalars override this."""
        return None

    def _batch_group_key(self, solver_params: Dict[str, Any]):
        """Hashable signature of everything that changes the PROGRAM (static
        shape/structure) for this estimator's solver — param sets with equal
        keys can solve as one batched program over the remaining (traced)
        hyperparameters. None (default) = this estimator never batches."""
        return None

    def _device_dataset_key(self, dataset: Any, ctx: Any) -> tuple:
        """(dataset identity fingerprint, columns, dtype, mesh shape) — what
        must match for a cached placement to be reusable by this fit."""
        from .data import dataset_fingerprint

        input_col, input_cols = self._get_input_columns()
        label_col = self.getOrDefault("labelCol") if self._supervised else None
        weight_col = (
            self.getOrDefault("weightCol")
            if self._use_weight_col and self.hasParam("weightCol") and self.isDefined("weightCol")
            else None
        )
        sparse_optim = (
            self.getOrDefault("enable_sparse_data_optim")
            if self.hasParam("enable_sparse_data_optim")
            else None
        )
        if sparse_optim is None and not self._supports_sparse_input:
            sparse_optim = False  # mirrors _pre_process_data's densify default
        id_col = (
            self.getOrDefault("idCol")
            if self.hasParam("idCol") and self.isDefined("idCol")
            else None
        )
        return (
            dataset_fingerprint(dataset),
            (
                input_col,
                tuple(input_cols) if input_cols else None,
                label_col,
                weight_col,
                id_col,
            ),
            (np.dtype(np.float32 if self._float32_inputs else np.float64).name, sparse_optim),
            tuple(int(d.id) for d in ctx.mesh.devices.flatten()),
        )

    def _admit_and_layout(
        self,
        extracted: ExtractedData,
        ctx: Any,
        stage_logger: Any,
        force_stream: bool = False,
        key: Optional[tuple] = None,
        source: Any = None,
        attempt: int = 0,
    ) -> DeviceDataset:
        """Admission verdict + the matching data plane (docs/robustness.md
        "Memory safety"): RESIDENT fits validate eagerly and lay out in HBM
        as before; an over-budget fit DEMOTES to the streaming plan
        (`fit.demotions`, reason logged and stamped on ``model._fit_metrics``)
        with per-block validation deferred to the pipeline; even-streaming-
        doesn't-fit raises the typed `HbmBudgetError` from `memory.admit_fit`.
        Streamed datasets return with ``key=None`` — NON-cacheable: there is
        no HBM placement to reuse, and a later attempt must re-budget."""
        from . import memory as _memory
        from . import telemetry
        from .data import run_deferred_validation
        from .parallel import chaos

        # hand back this fit call's PREVIOUS claim before re-admitting: a
        # retry/recovery/OOM-demotion re-entry still holds the failed
        # attempt's reservation, and the fresh admission must not count the
        # fit's own doomed bytes against itself (a resident fit at ~0.9x
        # budget would otherwise spuriously demote — or refuse — on retry)
        self._adopt_reservation(None)
        adm = _memory.admit_fit(self, extracted, ctx, force_stream=force_stream)  # ledger-ok: THE fit-side admission entry — reserves through the shared ledger
        self._last_admission = adm
        # the admission's shared-ledger claim now belongs to THIS fit call
        # (released in _call_fit_func's finally); the decision object itself
        # may be cached on the DeviceDataset and must not carry a live claim
        self._adopt_reservation(adm.reservation)
        adm.reservation = None
        if adm.verdict == _memory.STREAM:
            if telemetry.enabled():
                reg = telemetry.registry()
                reg.inc("memory.admission_stream")
                reg.inc("fit.demotions")
            get_logger(type(self)).warning(
                "fit demoted RESIDENT -> STREAM: %s (chunk_rows=%d)",
                adm.reason, adm.chunk_rows,
            )
            plan = StreamPlan(
                extracted=extracted,
                chunk_rows=adm.chunk_rows,
                validate=bool(config.get("validate_ingest", False)),
                admission=adm,
            )
            inputs = self._build_stream_inputs(extracted, ctx, plan)
            return DeviceDataset(
                key=None, extracted=extracted, inputs=inputs, source=source,
                admission=adm,
            )
        if telemetry.enabled():
            telemetry.registry().inc("memory.admission_resident")
        # the deferred opt-in NaN/Inf scan runs eagerly (full, chunked) before
        # any placement — resident semantics unchanged
        run_deferred_validation(extracted)
        # index = the retry/recovery attempt: `oom:stage=placement:round=1`
        # targets the RE-placement of a recovery attempt, not the first layout
        chaos.maybe_fail_oom("placement", attempt)
        with telemetry.span("layout", logger=stage_logger):
            inputs = self._build_fit_inputs(extracted, ctx)
        telemetry.record_device_memory()  # HBM watermark after placement
        return DeviceDataset(
            key=key, extracted=extracted, inputs=inputs, source=source,
            admission=adm,
        )

    def _build_stream_inputs(
        self, extracted: ExtractedData, ctx: Any, plan: StreamPlan
    ) -> FitInputs:
        """`FitInputs` for an out-of-core fit: NOTHING is placed — X is None,
        y/w are the HOST columns, and `stream` carries the plan the streaming
        solver drivers consume. Solvers treat host w == 0 rows as padding,
        so `with_row_mask` fold reuse works unchanged."""
        from .parallel import PartitionDescriptor

        mesh = ctx.mesh
        n_dev = mesh.devices.size
        dtype = np.float32 if self._float32_inputs else np.float64
        desc = PartitionDescriptor.build(
            [
                extracted.n_rows // n_dev + (1 if i < extracted.n_rows % n_dev else 0)
                for i in range(n_dev)
            ],
            extracted.n_cols,
        )
        w = extracted.weight
        w_np = (
            np.asarray(w, dtype=dtype)
            if w is not None
            else np.ones(extracted.n_rows, dtype=dtype)
        )
        return FitInputs(
            mesh=mesh,
            X=None,
            y=extracted.label,
            w=w_np,
            n_valid=desc.m,
            n_cols=extracted.n_cols,
            desc=desc,
            dtype=dtype,
            X_sparse=extracted.features if extracted.is_sparse else None,
            ctx=ctx,
            stream=plan,
        )

    def _device_dataset(
        self,
        dataset: Any,
        ctx: Any,
        stage_logger: Any,
        force_stream: bool = False,
        attempt: int = 0,
    ) -> DeviceDataset:
        """Ingest + admission + layout, or a cache hit inside an active
        `device_dataset_scope` — the ingest/layout spans (and their cost)
        exist only on a miss, which is how a numFolds x paramMaps
        CrossValidator fit performs exactly ONE ingest and ONE layout.
        Streamed (demoted) datasets are never cached; a cached entry is by
        construction a RESIDENT placement that already passed admission."""
        from . import memory as _memory
        from . import telemetry

        scope = _DDS_SCOPE.get()
        if scope is None or force_stream:
            with telemetry.span("ingest", logger=stage_logger):
                extracted = self._pre_process_data(
                    dataset, for_fit=True, defer_validation=True
                )
            return self._admit_and_layout(
                extracted, ctx, stage_logger, force_stream, attempt=attempt
            )
        key = self._device_dataset_key(dataset, ctx)
        allow_hit = True
        if ctx.is_spmd:
            # placement-fingerprint agreement, ONE rendezvous round: the
            # cache-hit branch below runs no collectives while the miss
            # branch runs the layout allgather, so hit/miss MUST be
            # symmetric across ranks. Every rank votes its have-bit; the
            # cache is used only when ALL ranks hold the exact entry —
            # otherwise every rank takes the rebuild branch together (a
            # rank that does hold the entry re-lands on the host-retained
            # path: same identity, ingest skipped, symmetric layout).
            with scope.lock:
                have = key in scope.cache
            votes = ctx.rendezvous.allgather(f"dds-have:{int(have)}")
            allow_hit = all(v == "dds-have:1" for v in votes)
            telemetry.registry().inc("fit.device_dataset_spmd_rounds")
            # a rank that holds the entry while others miss takes the
            # host-retained path below (`same_ingest_identity` is reflexive):
            # its ingest is skipped but admission + layout re-run, keeping
            # every rank's collective schedule identical
        # one builder per scope: a cache-miss build is never duplicated by a
        # concurrent fit sharing the scope
        with scope.lock:  # held-ok: the scope (and its lock) is context-local — each SPMD rank holds only its own — and the partition-build allgather below is symmetric across ranks: the pre-lock fingerprint round guarantees every rank enters the same branch
            dds = scope.cache.get(key) if allow_hit else None
            if dds is not None:
                scope.cache[key] = scope.cache.pop(key)  # LRU: move to newest
                telemetry.registry().inc("fit.device_dataset_reuses")
                if dds.admission is not None:
                    # a cache hit skipped _admit_and_layout: re-stamp the
                    # verdict that admitted the reused placement, and
                    # re-reserve its bytes in the shared ledger (the
                    # placement is physically held; serving loads and other
                    # tenants must see it — docs/scheduling.md)
                    self._last_admission = dds.admission
                    self._adopt_reservation(
                        _memory.rereserve_admission(dds.admission)
                    )
            else:
                # host-retained re-placement (docs/robustness.md "Elastic
                # recovery"): a cached entry for the SAME data on a DIFFERENT
                # mesh — the survivor re-mesh shape, where the device set
                # changed under one fit — still holds the right host blocks.
                # Reuse them: the ingest pass is skipped entirely and only
                # the admission + layout run against the new mesh (fewer
                # chips shrink the budget: a resident fit may legitimately
                # RESUME AS A STREAMING FIT here).
                from .data import same_ingest_identity

                retained = next(
                    (e for ek, e in scope.cache.items() if same_ingest_identity(ek, key)),
                    None,
                )
                if retained is not None:
                    extracted = retained.extracted
                    reg = telemetry.registry()
                    reg.inc("recovery.replacements")
                    reg.inc("recovery.rows_replaced", int(extracted.n_rows))
                    dds = self._admit_and_layout(
                        extracted, ctx, stage_logger, key=key,
                        source=retained.source, attempt=attempt,
                    )
                else:
                    with telemetry.span("ingest", logger=stage_logger):
                        extracted = self._pre_process_data(
                            dataset, for_fit=True, defer_validation=True
                        )
                    # `source=dataset` pins the object so its id() — the
                    # heart of the cache key — cannot be recycled while the
                    # entry lives
                    dds = self._admit_and_layout(
                        extracted, ctx, stage_logger, key=key, source=dataset,
                        attempt=attempt,
                    )
                    if dds.key is not None:
                        telemetry.registry().inc("fit.device_dataset_builds")
                if dds.key is not None:  # streamed datasets are non-cacheable
                    scope.cache[key] = dds
                # bounded retention: a scope around a loop over FRESH dataset
                # objects (per-fold slices on a non-engine path) must not
                # stack HBM placements — evict least-recently-used entries
                # (in-flight fits keep their own references; eviction only
                # drops the cache's pin)
                cap = max(1, int(config.get("device_dataset_cache_entries", 2)))
                while len(scope.cache) > cap:
                    evicted = next(iter(scope.cache))
                    del scope.cache[evicted]
                    telemetry.registry().inc("fit.device_dataset_evictions")
            scope.last = dds
        return dds

    def _call_fit_func(
        self,
        dataset: Any,
        param_maps: Optional[List[Dict[Param, Any]]],
        row_mask: Optional[np.ndarray] = None,
    ) -> List[Dict[str, Any]]:
        """Run the (possibly multi-model) fit: ONE data layout, N solver calls.

        Parity with the reference's single-pass `fitMultiple` (core.py:877-911):
        the feature block is placed in HBM once; each param-map's solver call
        reuses it. Returns one model-attribute dict per param map (or a single
        one when param_maps is None).

        Stage timing rides on `telemetry.span` (ingest/layout/solve): spans
        feed the metrics registry + JSONL sink when telemetry is on and log
        the old ``stage <name>: <t>s`` lines when `verbose` is set — one
        mechanism instead of parallel hand-rolled timing. The per-fit
        registry delta lands on models as ``_fit_metrics``
        (see `_TpuEstimator._fit_internal`).
        """
        import contextlib

        from . import telemetry

        logger = get_logger(type(self))
        self._last_admission = None  # per-fit; stamped onto _fit_metrics below
        verbose = bool(self._solver_params.get("verbose"))
        stage_logger = logger if verbose else None
        # Opt-in tracing (the NVTX/xprof analog, SURVEY.md §5): when
        # SRML_PROFILE_DIR is set, the whole fit runs under a jax.profiler
        # trace viewable in xprof/tensorboard. The trace must begin BEFORE the
        # fit/ingest spans open — a TraceAnnotation entered outside an active
        # trace is not captured, and docs/observability.md promises every
        # stage span as an xprof annotation.
        profile_dir = os.environ.get("SRML_PROFILE_DIR")
        profile_cm: Any = contextlib.nullcontext()
        if profile_dir:
            import jax

            profile_cm = jax.profiler.trace(profile_dir)  # profiler-ok: the opt-in SRML_PROFILE_DIR xprof hook — this IS the sanctioned whole-fit trace entry point
        from . import diagnostics
        from .parallel import TpuContext

        active = TpuContext.current()
        # trace identity OUTERMOST: every span/metric/flight-recorder record
        # of this fit (including the fit_scope snapshot) carries the same
        # trace_id + fit_id on every rank — under SPMD, rank 0 mints the id
        # and propagates it through one rendezvous round (docs/observability.md
        # "Trace correlation")
        try:
            with diagnostics.trace_scope(
                type(self).__name__, active
            ), profile_cm, telemetry.fit_scope(
                type(self).__name__
            ) as tele_scope, telemetry.span(
                "fit", logger=stage_logger, estimator=type(self).__name__
            ):
                # the whole traced fit (ingest -> layout -> solve) is ONE
                # recoverable stage: a transient retry re-derives its state from
                # the immutable dataset (bit-identical to an unfaulted fit —
                # pinned by tests/test_chaos.py), and a rank loss on a
                # reform-capable rendezvous opens a recovery epoch — the
                # survivor mesh re-ingests from host-retained chunks and the
                # solvers resume from the checkpoint store
                rows = recoverable_stage(
                    lambda attempt: self._call_fit_func_traced(
                        dataset, param_maps, logger, stage_logger, row_mask,
                        attempt=attempt,
                    ),
                    stage="fit",
                    ctx=active,
                    logger=logger,
                )
        finally:
            # the fit's shared-ledger claim ends with the fit — success,
            # failure, or preemption (the workspace is gone; a scope-cached
            # placement re-reserves on its next cache hit)
            self._adopt_reservation(None)
        self._last_fit_metrics = tele_scope["metrics"]
        eff = tele_scope.get("efficiency")
        if eff and isinstance(self._last_fit_metrics, dict):
            # the fit's device-time attribution (execute/compile/host/idle
            # split + per-stage detail) and its compile-ledger delta ride the
            # per-fit metrics, mirroring the admission stamp below
            self._last_fit_metrics = dict(self._last_fit_metrics)
            self._last_fit_metrics["efficiency"] = eff
            self._last_fit_metrics["compile"] = eff.get("compile", {})
        adm = getattr(self, "_last_admission", None)
        if (
            adm is not None
            and isinstance(self._last_fit_metrics, dict)
            and (telemetry.enabled() or adm.demoted)
        ):
            # stamp the admission verdict (and a demotion's reason) onto the
            # per-fit metrics so models carry WHY they streamed. A DEMOTED
            # fit stamps even with telemetry off — the reason a fit streamed
            # is robustness state, not a metric — while a plain resident fit
            # keeps the disabled-telemetry contract: _fit_metrics == {}
            self._last_fit_metrics = dict(self._last_fit_metrics)
            self._last_fit_metrics["admission"] = adm.stamp()
        return rows

    def _call_fit_func_traced(
        self,
        dataset: Any,
        param_maps: Optional[List[Dict[Param, Any]]],
        logger: Any,
        stage_logger: Any,
        row_mask: Optional[np.ndarray] = None,
        attempt: int = 0,
    ) -> List[Dict[str, Any]]:
        """One recoverable attempt, with the OOM conversion ladder wrapped
        around it: a REAL backend out-of-memory failure at placement or solve
        (XLA RESOURCE_EXHAUSTED — or the chaos `oom` injection shaped like
        one) is converted to the typed `HbmBudgetError` and retried ONCE on
        the out-of-core streaming path. The retry re-ingests and streams; if
        it OOMs too (or the estimator has no streaming path / runs SPMD), the
        typed error propagates — a raw XLA error never does. `attempt` is the
        retry/recovery attempt index — the chaos `oom:stage=placement` index,
        so a plan can target the RE-placement of a recovery attempt
        (`round=1`) rather than the first layout."""
        from . import memory as _memory
        from . import telemetry
        from .parallel import TpuContext

        try:
            return self._call_fit_func_attempt(
                dataset, param_maps, logger, stage_logger, row_mask,
                attempt=attempt,
            )
        except Exception as e:
            if not _memory.is_oom_error(e):
                raise
            if telemetry.enabled():
                telemetry.registry().inc("memory.oom_caught")
            active = TpuContext.current()
            if not getattr(self, "_supports_streaming_fit", False) or (
                active is not None and active.is_spmd
            ):
                raise _memory.as_hbm_budget_error(e) from e
            logger.warning(
                "backend out-of-memory during fit (%s); converting to "
                "HbmBudgetError and retrying ONCE on the out-of-core "
                "streaming path", e,
            )
        # the retry runs OUTSIDE the except handler: the handler's traceback
        # pins the failed attempt's frames — and with them the dead resident
        # placement's device arrays — for as long as `e` lives; Python drops
        # `e` at handler exit, so by here that HBM is release-able. Any
        # placements cached by an enclosing device_dataset_scope are evicted
        # too: under a real allocation failure, a cache hit is worth less
        # than the streaming retry having room to run.
        scope = _DDS_SCOPE.get()
        if scope is not None:
            with scope.lock:
                n_evicted = len(scope.cache)
                scope.cache.clear()
            if n_evicted and telemetry.enabled():
                telemetry.registry().inc("fit.device_dataset_evictions", n_evicted)
        try:
            return self._call_fit_func_attempt(
                dataset, param_maps, logger, stage_logger, row_mask,
                attempt=attempt, force_stream=True,
            )
        except Exception as e2:
            if _memory.is_oom_error(e2):
                raise _memory.as_hbm_budget_error(e2) from e2
            raise

    def _call_fit_func_attempt(
        self,
        dataset: Any,
        param_maps: Optional[List[Dict[Param, Any]]],
        logger: Any,
        stage_logger: Any,
        row_mask: Optional[np.ndarray] = None,
        attempt: int = 0,
        force_stream: bool = False,
    ) -> List[Dict[str, Any]]:
        import contextlib

        from . import telemetry
        from .parallel import TpuContext
        from .parallel.mesh import dtype_scope, ensure_compilation_cache

        compile_cache_on = ensure_compilation_cache()

        # Route through the caller's process group when one is active (the
        # reference's train-UDF-inside-CumlContext shape, core.py:768-781);
        # otherwise stand up the single-controller context ourselves.
        active = TpuContext.current()
        if active is not None:
            if active.is_spmd and not self._supports_multiprocess:
                raise NotImplementedError(
                    f"{type(self).__name__} does not support multi-process SPMD fit yet; "
                    "run it single-controller (one process driving all devices)"
                )
            ctx_mgr: Any = contextlib.nullcontext(active)
        else:
            from .parallel.mesh import default_devices

            ctx_mgr = TpuContext(
                0, 1, num_devices=min(self.num_workers, len(default_devices()))
            )

        with ctx_mgr as ctx, dtype_scope(
            np.float32 if self._float32_inputs else np.float64, self._matmul_precision
        ):
            dds = self._device_dataset(
                dataset, ctx, stage_logger, force_stream=force_stream,
                attempt=attempt,
            )
            extracted, inputs = dds.extracted, dds.inputs
            fit_func = self._get_tpu_fit_func(extracted)
            if row_mask is not None:
                # under SPMD each rank passes its LOCAL fold mask; the fold
                # is the union of per-rank train rows (with_row_mask pads to
                # the agreed local target, so shapes stay symmetric)
                inputs = inputs.with_row_mask(row_mask)
            logger.info(
                "fit: %d rows x %d cols on %d-device mesh (%s)%s",
                inputs.n_valid, inputs.n_cols, inputs.mesh.devices.size,
                "sparse" if inputs.X_sparse is not None else "dense",
                f" [SPMD rank {ctx.rank}/{ctx.nranks}]" if ctx.is_spmd else "",
            )
            if param_maps is None:
                solver_param_sets = [dict(self._solver_params)]
            else:
                solver_param_sets = []
                for pm in param_maps:
                    est = self.copy(pm)
                    # re-sync spark params -> solver params for overridden entries
                    mapping = est._param_mapping()
                    for p, v in pm.items():
                        name = p.name if isinstance(p, Param) else p
                        mapped = mapping.get(name, None)
                        if mapped:
                            est._set_solver_param(mapped, v, silent=True)
                    solver_param_sets.append(dict(est._solver_params))
            rows, solve_times = self._dispatch_solves(
                inputs, extracted, fit_func, solver_param_sets, stage_logger
            )
            # compile-vs-execute first-call probe: valid ONLY when the solver
            # param sets are identical SEQUENTIAL re-runs of one program —
            # different maps change the work itself (e.g. a maxIter grid),
            # and after sweep batching a whole grid is ONE solve, leaving a
            # single time with nothing to difference against
            if len(solve_times) > 1 and all(
                sp == solver_param_sets[0] for sp in solver_param_sets[1:]
            ):
                telemetry.registry().gauge(
                    "fit.compile_overhead_s_est", solve_times[0] - min(solve_times[1:])
                )
            if solve_times and compile_cache_on:
                # first-call wall time under the persistent compilation cache:
                # across bench rounds this gauge falling toward the repeat
                # solve time IS the cache working (docs/observability.md)
                telemetry.registry().gauge("fit.compile_cache_hit", solve_times[0])
            telemetry.record_device_memory()  # HBM watermark after solve
            if telemetry.enabled():
                # analytic FLOP estimate (the `_solver_workspace_terms`
                # sibling hook) feeds the MFU gauge's numerator — per solve,
                # so a sweep's N param sets scale it N-fold
                fhook = getattr(self, "_solver_flop_estimate", None)
                if fhook is not None:
                    try:
                        flops = fhook(int(inputs.n_valid), int(inputs.n_cols))
                    except Exception:
                        flops = None
                    if flops:
                        telemetry.note_flops(
                            float(flops) * max(1, len(solver_param_sets)),
                            chips=int(inputs.mesh.devices.size),
                        )
        return rows

    def _dispatch_solves(
        self,
        inputs: FitInputs,
        extracted: ExtractedData,
        fit_func: FitFunc,
        solver_param_sets: List[Dict[str, Any]],
        stage_logger: Any,
    ) -> Tuple[List[Dict[str, Any]], List[float]]:
        """Run every solver param set, batching where possible.

        Param sets whose `_batch_group_key` signatures match differ only in
        hyperparameters the solver takes as TRACED scalars — those groups
        solve as ONE compiled program (`_get_tpu_batched_fit_func`); sets
        that change program structure (maxIter, k, solver selection) run the
        classic sequential loop. `fit.solves_batched` / `fit.solves_sequential`
        count how each param set was dispatched."""
        from . import telemetry
        from .parallel import chaos

        chaos.maybe_fail_oom("solve")  # round-less `oom:stage=solve` plans
        n_sets = len(solver_param_sets)
        rows: List[Optional[Dict[str, Any]]] = [None] * n_sets
        solve_times: List[float] = []
        # streaming fits solve sequentially: the batched sweeps are compiled
        # over the RESIDENT placement (inputs.X / one placed ELL set)
        batched_fn = (
            self._get_tpu_batched_fit_func(extracted)
            if n_sets > 1 and inputs.stream is None
            else None
        )

        groups: Dict[Any, List[int]] = {}
        order: List[Any] = []
        for i, sp in enumerate(solver_param_sets):
            key = self._batch_group_key(sp) if batched_fn is not None else None
            gid = ("seq", i) if key is None else ("batch", key)
            if gid not in groups:
                groups[gid] = []
                order.append(gid)
            groups[gid].append(i)

        # compile-ledger shape-class: coarse on purpose — what the jit cache
        # keys on that the OUTSIDE can see (padded dims, layout, mesh width).
        # Hyperparameters that re-trace (maxIter grids) are a documented bias
        # of the ledger, not part of the key (docs/observability.md).
        shape_class = (
            f"{inputs.n_valid}x{inputs.n_cols}"
            f":{'sparse' if inputs.X_sparse is not None else 'dense'}"
            f":{'stream' if inputs.stream is not None else 'resident'}"
            f":mesh{int(inputs.mesh.devices.size)}"
        )
        for gid in order:
            idxs = groups[gid]
            if batched_fn is not None and gid[0] == "batch" and len(idxs) > 1:
                with telemetry.span(
                    "solve", logger=stage_logger, batched=len(idxs), of=n_sets
                ) as solve_span, telemetry.compile_event(
                    f"fit.{type(self).__name__}.batched",
                    f"{shape_class}:n{len(idxs)}",
                ):
                    out = batched_fn(inputs, [solver_param_sets[i] for i in idxs])
                if out is not None:
                    if len(out) != len(idxs):  # fail at the contract breach,
                        # not as a far-away TypeError on a None attrs dict
                        raise RuntimeError(
                            f"{type(self).__name__} batched fit returned "
                            f"{len(out)} results for {len(idxs)} param sets"
                        )
                    if solve_span.wall_s is not None:
                        solve_times.append(solve_span.wall_s)
                    telemetry.registry().inc("fit.solves_batched", len(idxs))
                    for i, attrs in zip(idxs, out):
                        rows[i] = attrs
                    continue
                # declined at runtime (degenerate data, convergence tracing
                # active): fall through to the sequential loop below
            for i in idxs:
                with telemetry.span(
                    "solve", logger=stage_logger, index=i, of=n_sets
                ) as solve_span, telemetry.compile_event(
                    f"fit.{type(self).__name__}", shape_class
                ):
                    rows[i] = fit_func(inputs, solver_param_sets[i])
                if solve_span.wall_s is not None:
                    solve_times.append(solve_span.wall_s)
                telemetry.registry().inc("fit.solves_sequential")
        return rows, solve_times


class _TpuEstimator(_TpuCaller):
    """Estimator base (reference `_CumlEstimator`, core.py:853-1074)."""

    def fit(
        self,
        dataset: Any,
        params: Optional[Union[Dict, List[Dict]]] = None,
        warm_start_from: Any = None,
    ):
        """Fit on `dataset`. `warm_start_from` seeds the solver from a
        previous result's PORTABLE iterate (docs/scheduling.md "Warm
        starts") instead of a cold init: a fitted model of the same
        estimator family (k-means centers, the GLM coefficient iterate) or a
        `checkpoint.SolverCheckpoint` (the PR-6 portable subset — what a
        preempted/recovered fit resumes from, now a public API). Estimators
        whose solvers have no iterate to seed (closed-form linear/PCA,
        DBSCAN/UMAP) raise `NotImplementedError`; a shape-mismatched donor
        raises `ValueError`. Adoption is counted (``fit.warm_starts``) along
        with the donor's already-paid iterations
        (``fit.warm_start_iterations_saved``)."""
        if isinstance(params, (list, tuple)):
            if warm_start_from is not None:
                raise ValueError(
                    "warm_start_from is a single-fit seed; combine it with "
                    "one param dict, not a param-map list"
                )
            return [m for _, m in sorted(self.fitMultiple(dataset, list(params)))]
        if isinstance(params, dict) and params:
            return self.copy(params).fit(dataset, warm_start_from=warm_start_from)
        if warm_start_from is not None:
            self._warm_start = self._resolve_warm_start(warm_start_from)
        try:
            models = self._fit_internal(dataset, None)
        finally:
            self._warm_start = None
        return models[0]

    def _resolve_warm_start(self, source: Any) -> Dict[str, Any]:
        """Per-estimator hook: extract the portable warm-start payload from
        `source` (a fitted model or a `SolverCheckpoint`). Overridden by the
        iterative estimators (KMeans, LogisticRegression); the default names
        the gap instead of silently cold-starting."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support warm_start_from: its "
            "solver has no portable iterate to seed (closed-form or "
            "non-iterative fit)"
        )

    def fitMultiple(self, dataset: Any, paramMaps: Sequence[Dict[Param, Any]]) -> "_FitMultipleIterator":
        """Train all param maps in ONE pass over the data (reference core.py:877-911)."""

        def fitMultipleModels() -> List["_TpuModel"]:
            return self._fit_internal(dataset, list(paramMaps))

        return _FitMultipleIterator(fitMultipleModels, len(paramMaps))

    def _fit_internal(
        self,
        dataset: Any,
        paramMaps: Optional[List[Dict[Param, Any]]],
        row_mask: Optional[np.ndarray] = None,
    ) -> List["_TpuModel"]:
        attr_rows = self._call_fit_func(dataset, paramMaps, row_mask)
        fit_metrics = getattr(self, "_last_fit_metrics", {})
        models = []
        for i, attrs in enumerate(attr_rows):
            model = self._create_model(attrs)
            model._model_attributes = attrs
            model._fit_metrics = fit_metrics
            self._copyValues(model, paramMaps[i] if paramMaps else None)
            self._copy_solver_params(model)
            if paramMaps:
                est = self.copy(paramMaps[i])
                est._copy_solver_params(model)
                model._solver_params.update(
                    {k: v for k, v in est._solver_params.items()}
                )
            models.append(model)
        return models

    @abstractmethod
    def _create_model(self, attrs: Dict[str, Any]) -> "_TpuModel":
        raise NotImplementedError

    def _supportsTransformEvaluate(self, evaluator: Any) -> bool:
        """Whether CrossValidator can use the fused multi-model evaluate path
        (reference `_CumlEstimator._supportsTransformEvaluate`)."""
        return False

    # persistence ---------------------------------------------------------
    def write(self) -> "_TpuWriter":
        return _TpuWriter(self)

    def save(self, path: str) -> None:
        self.write().save(path)

    @classmethod
    def read(cls) -> "_TpuReader":
        return _TpuReader(cls)

    @classmethod
    def load(cls, path: str):
        return cls.read().load(path)


class _TpuEstimatorSupervised(_TpuEstimator):
    """Adds label handling (reference `_CumlEstimatorSupervised`, core.py:1075-1114)."""

    _supervised = True


class _FitMultipleIterator:
    """Thread-safe (index, model) iterator; ALL models come from one fit pass
    (reference `_FitMultipleIterator`, core.py:808-850)."""

    def __init__(self, fitMultipleModels: Callable[[], List["_TpuModel"]], numModels: int):
        self.fitMultipleModels = fitMultipleModels
        self.numModels = numModels
        self.counter = 0  # guarded-by: lock
        self.lock = lockcheck.make_lock("core._FitMultipleIterator.lock")
        # written once by the index-0 claimant, then published through
        # `_materialized`; readers wait on the event, never the lock
        self.models: Optional[List["_TpuModel"]] = None
        self._materialized = threading.Event()
        self._fit_error: Optional[BaseException] = None

    def __iter__(self) -> Iterator[Tuple[int, "_TpuModel"]]:
        return self

    def __next__(self) -> Tuple[int, "_TpuModel"]:
        # the lock covers ONLY index claiming: the single fit pass used to
        # run inside it, which held the iterator lock across rendezvous
        # rounds and sink I/O (ci/analysis `blocking-under-lock`) — every
        # concurrent consumer was blocked on the MUTEX instead of on the
        # models being ready
        with self.lock:
            index = self.counter
            if index >= self.numModels:
                raise StopIteration()
            self.counter += 1
        if index == 0:
            try:
                self.models = self.fitMultipleModels()
            except BaseException as e:
                self._fit_error = e
                raise
            finally:
                self._materialized.set()
        else:
            self._materialized.wait()  # blocking-ok: bounded by the claimant's fit, which owns the retry/rendezvous deadlines (core.retryable_stage)
            if self._fit_error is not None:
                raise RuntimeError(
                    "the fit pass materializing this iterator's models failed"
                ) from self._fit_error
        return index, self.models[index]

    next = __next__


class _TpuModel(_TpuCommon):
    """Model base (reference `_CumlModel`, core.py:1117-1488)."""

    def __init__(self, **model_attrs: Any) -> None:
        super().__init__()
        self._model_attributes: Dict[str, Any] = model_attrs
        # per-fit telemetry delta (counters/spans/gauges captured during the
        # fit that produced this model); {} when telemetry was disabled
        self._fit_metrics: Dict[str, Any] = {}
        # serving-plane state stamped by serving.ModelRegistry (docs/serving.md):
        # the admission verdict that loaded (or refused/evicted) this model,
        # mirroring the fit-side _fit_metrics["admission"] stamp
        self._serve_metrics: Dict[str, Any] = {}

    @property
    def hasSummary(self) -> bool:
        return False

    def transform(self, dataset: Any):
        raise NotImplementedError

    def _transform_evaluate(self, dataset: Any, evaluator: Any) -> List[float]:
        raise NotImplementedError(f"{type(self).__name__} does not support transform-evaluate")

    @classmethod
    def _transformEvaluate_supported(cls, evaluator: Any) -> bool:
        return False

    def _combine(self, models: List["_TpuModel"]) -> "_TpuModel":
        raise NotImplementedError

    # serving hooks (docs/serving.md) -------------------------------------
    # The per-estimator surface the serving plane composes: a resident
    # PredictProgram factory, plus the placement / per-bucket workspace byte
    # terms the admission budgeter (memory.admit_model_load) charges — the
    # serve-side analog of the fit-side `_solver_workspace_terms` hook.

    # serving dtypes this model accepts; the distance-core models extend
    # with "bf16" (their fast-bf16 scoring is parity-tested)
    _serve_dtypes: tuple = (None, "float32", "float64")

    def _serve_program(
        self, serve_dtype: Optional[str] = None, *, cap: Optional[int] = None
    ) -> "PredictProgram":
        """Resident predict handle for the serving plane. Models without a
        batched predict surface (DBSCAN's fused fit-transform, UMAP's
        fit-embedding) have nothing to keep resident."""
        raise NotImplementedError(
            f"{type(self).__name__} has no serving hook (no batched predict "
            "surface to keep resident)"
        )

    def _serve_check(self, serve_dtype: Optional[str] = None) -> None:
        """Cheap serveability preflight: raises exactly what `_serve_program`
        would, WITHOUT placing anything on device. The registry runs this
        before its admission/eviction loop, so a load that can never succeed
        (no hook, bad serve_dtype, unbound item set) cannot evict resident
        models as a side effect."""
        if type(self)._serve_program is _TpuModel._serve_program:
            self._serve_program(serve_dtype)  # the standard NotImplementedError
        if serve_dtype not in self._serve_dtypes:
            raise ValueError(
                f"{type(self).__name__} serves at its fit dtype; "
                f"serve_dtype={serve_dtype!r} is only available on the "
                "distance-core models (docs/serving.md)"
            )
        self._serve_n_cols()

    def _serve_n_cols(self) -> int:
        """Feature width the serving plane prewarms/validates against."""
        n = int(getattr(self, "n_cols", 0) or 0)
        if n <= 0:
            raise ValueError(
                f"{type(self).__name__} does not know its feature width; "
                "cannot prewarm the serving ladder"
            )
        return n

    def _serve_placement_terms(self) -> Dict[str, int]:
        """Per-device HBM bytes of this model's RESIDENT state (the arrays
        `construct()` places), as named terms for the admission budgeter.
        Default: every array model attribute at the serving working dtype —
        model state is replicated, so per-device cost is the full size."""
        itemsize = 4 if self._float32_inputs else 8
        total = 0
        for v in self._model_attributes.values():
            if isinstance(v, np.ndarray):
                total += int(v.size) * itemsize
            elif isinstance(v, (list, tuple)) and v and isinstance(v[0], np.ndarray):
                total += sum(int(a.size) for a in v) * itemsize
        return {"placement.params": total}

    def _serve_workspace_terms(
        self, bucket_rows_count: int, itemsize: int
    ) -> Dict[str, int]:
        """Per-bucket predict workspace estimate: bytes live during ONE
        dispatched batch of `bucket_rows_count` rows beyond the model state
        and the input block itself. {} (default) = no modeled workspace."""
        return {}

    def _record_bucket(self, xp: np.ndarray, n_valid: int, on_mesh: bool) -> None:
        """Bucket-ladder telemetry: rows padded, and — via a process-wide set
        of (model class, bucketed shape, dtype, placement) signatures — a
        `transform.bucket_programs` counter that advances only when a NEW
        bucketed shape reaches `predict`. The shape set deliberately
        survives `registry().reset()`: it mirrors the process-wide jit
        cache, which a registry reset does not clear — a shape seen before
        genuinely compiles nothing, so re-counting it would overstate
        compile work. Readers wanting per-window numbers take counter
        DELTAS. Asserting the counter stays at the ladder size while batch
        sizes vary freely is the test-side proof that serving compiles per
        bucket, not per tail shape."""
        from . import telemetry

        if not telemetry.enabled():
            return
        reg = telemetry.registry()
        reg.inc("transform.bucket_pad_rows", int(xp.shape[0]) - int(n_valid))
        sig = (type(self).__name__, tuple(xp.shape), str(xp.dtype), on_mesh)
        with _BUCKET_LOCK:
            if sig not in _BUCKET_SHAPES:
                _BUCKET_SHAPES.add(sig)
                reg.inc("transform.bucket_programs")

    # Spark JVM interop: name of the `spark_interop` converter for this model
    # class (None = the reference has no `.cpu()` for it either)
    _spark_converter: Optional[str] = None

    def cpu(self):
        """Equivalent GENUINE pyspark.ml JVM model built via py4j, usable in
        existing Spark pipelines and JVM serving (the reference's `.cpu()`
        capability: tree.py:524-569 + utils.py:311-481 for forests,
        feature.py:365-379 PCA, regression.py:658-672, classification.py:
        1301-1323). Requires pyspark and an active SparkSession; cached after
        the first conversion."""
        if self._spark_converter is None:
            raise NotImplementedError(
                f"{type(self).__name__} has no Spark-ML JVM equivalent (reference parity)"
            )
        if getattr(self, "_spark_model", None) is None:
            from . import spark_interop

            self._spark_model = getattr(spark_interop, self._spark_converter)(self)
        return self._spark_model

    # persistence ---------------------------------------------------------
    def write(self) -> "_TpuWriter":
        return _TpuWriter(self)

    def save(self, path: str) -> None:
        self.write().save(path)

    @classmethod
    def read(cls) -> "_TpuReader":
        return _TpuReader(cls)

    @classmethod
    def load(cls, path: str):
        return cls.read().load(path)


# Process-wide record of bucketed shapes already handed to a `predict`
# program (see `_TpuModel._record_bucket`).
_BUCKET_LOCK = lockcheck.make_lock("core._BUCKET_LOCK")
_BUCKET_SHAPES: set = set()  # guarded-by: _BUCKET_LOCK


class PredictProgram:
    """Resident, reusable predict handle — the internals of
    `_TpuModelWithColumns._transform_arrays` (construct the device state once,
    bucket-pad every batch up the geometric ladder, run the jitted `predict`,
    slice outputs back) exposed as ONE object with a lifetime.

    Two consumers share it so they cannot drift: `_transform_arrays` builds a
    short-lived one per transform call, and the serving plane
    (`spark_rapids_ml_tpu/serving/`, docs/serving.md) holds one per RESIDENT
    model for the model's whole registry lifetime — which is what makes a
    long-lived scoring service compile-free after load-time prewarm.

    The async contract (enforced by the ci/analysis `serve-dispatch` rule):

      * `dispatch(xb)` pads a host batch UP the bucket ladder
        (`mesh.bucket_rows`) and runs `predict` WITHOUT any host fetch — the
        returned device arrays are in flight when it returns;
      * `fetch(result, n_valid)` is the one device→host sync point, slicing
        every output back to the valid rows;
      * `prewarm(...)` dispatches zeros through every ladder rung (through
        the persistent compile cache, `mesh.ensure_compilation_cache`) so a
        resident model's first query pays dispatch, never compile.
    """

    def __init__(
        self,
        model: "_TpuModel",
        *,
        construct: Optional[Callable[[], Any]] = None,
        predict: Optional[Callable[[Any, Any], Any]] = None,
        cap: Optional[int] = None,
        mesh: Any = None,
    ) -> None:
        import jax

        from .parallel.mesh import replicated

        if construct is None or predict is None:
            c0, p0, _ = model._get_transform_func()
            construct = construct or c0
            predict = predict or p0
        self.model = model
        self.predict_fn = predict
        self.mesh = mesh
        self.multiple = int(mesh.devices.size) if mesh is not None else 1
        self.cap = int(cap) if cap else int(config["max_records_per_batch"]) * self.multiple
        self.bucket_min = int(config["transform_bucket_min_rows"])
        self.dtype = np.float32 if model._float32_inputs else np.float64
        state = construct()
        if mesh is not None:
            state = jax.tree.map(
                lambda a: jax.device_put(a, replicated(mesh))
                if isinstance(a, (np.ndarray, jax.Array))
                else a,
                state,
            )
        self.state = state
        # per-program record of bucketed shapes already dispatched — what the
        # serving engine's `serve.bucket_hits` counter reads (independent of
        # the telemetry-gated process-wide `transform.bucket_programs` set)
        self._shapes_seen: set = set()
        self.last_dispatch_new_shape: bool = False

    def ladder(self, max_rows: Optional[int] = None) -> List[int]:
        """The rung sizes (rows) batches of 1..max_rows pad up to — exactly
        what `prewarm` compiles (`mesh.bucket_ladder`)."""
        from .parallel.mesh import bucket_ladder

        return bucket_ladder(
            min(int(max_rows), self.cap) if max_rows else self.cap,
            multiple=self.multiple,
            min_rows=self.bucket_min,
            cap=self.cap,
        )

    def dispatch(self, xb: np.ndarray) -> Tuple[Any, int]:
        """Pad one host batch up its bucket rung and run `predict` — NO host
        fetch; returns (in-flight result, valid row count). A zero-row batch
        still dispatches one bucket-padded rung so multi-output models yield
        one correctly-shaped empty array per output at `fetch`."""
        import jax

        from .parallel.mesh import bucket_rows, row_sharding

        xb = np.asarray(xb)
        xp, n_valid = bucket_rows(
            xb, multiple=self.multiple, min_rows=self.bucket_min, cap=self.cap
        )
        self.model._record_bucket(xp, n_valid, self.mesh is not None)
        sig = (tuple(xp.shape), str(xp.dtype))
        self.last_dispatch_new_shape = sig not in self._shapes_seen
        self._shapes_seen.add(sig)
        if self.mesh is not None:
            xp = jax.device_put(xp, row_sharding(self.mesh, xp.ndim))
        return self.predict_fn(self.state, xp), n_valid

    def fetch(self, result: Any, n_valid: int) -> Any:
        """THE device→host sync point: materialize the in-flight result and
        slice every output back to the valid rows."""
        from . import telemetry

        with telemetry.device_wait("predict_fetch"):
            if isinstance(result, tuple):
                return tuple(np.asarray(r)[:n_valid] for r in result)
            return np.asarray(result)[:n_valid]

    def prewarm(self, n_cols: int, *, max_rows: Optional[int] = None) -> int:
        """Compile every ladder rung up to `max_rows` rows by dispatching a
        zeros batch per rung and blocking on it (the compile must complete at
        LOAD time, not at the first query). With a persistent compile cache
        configured the programs come off disk. Returns the rung count.

        Each rung is one compile-ledger entry (`telemetry.compile_event`):
        the load-time compile wall lands in `compile.*` instead of hiding in
        `serve_load`'s span."""
        from . import telemetry

        rungs = self.ladder(max_rows)
        for r in rungs:
            with telemetry.compile_event(
                f"predict.{type(self.model).__name__}", f"{r}x{int(n_cols)}"
            ):
                result, _ = self.dispatch(
                    np.zeros((r, int(n_cols)), dtype=self.dtype)
                )
                self.fetch(result, 0)
        return len(rungs)


class _TpuModelWithColumns(_TpuModel):
    """Transform = append prediction column(s), batched over rows
    (reference `_CumlModelWithColumns`, core.py:1490-1649).

    The per-batch loop is the analog of the reference's pandas_udf Arrow-batch
    loop (core.py:1562-1572); `construct` runs once (model attrs -> device
    arrays), `predict` is jitted and reused across batches.
    """

    @abstractmethod
    def _get_transform_func(self) -> TransformFuncs:
        raise NotImplementedError

    def _serve_program(
        self, serve_dtype: Optional[str] = None, *, cap: Optional[int] = None
    ) -> PredictProgram:
        """Default serving hook: the model's own (construct, predict) pair as
        a resident PredictProgram. `serve_dtype` outside `_serve_dtypes` is
        rejected — the bf16 query path exists only on the distance-core
        models (KMeansModel, NearestNeighborsModel), whose fast-bf16 scoring
        is parity-tested in ops/distance.py (docs/serving.md "bf16 serving")."""
        self._serve_check(serve_dtype)
        return PredictProgram(self, cap=cap)

    def _out_column_names(self) -> List[str]:
        """Names of appended columns; single-entry list for plain predictors."""
        return [self.getOrDefault("outputCol") if self.hasParam("outputCol") and self.isDefined("outputCol") else pred.prediction]

    def _transform_arrays(self, features: Any) -> Any:
        """Batched predict over a host feature block. The per-algo `predict` may
        return one array or a tuple of arrays (multi-output models); each output
        is concatenated across batches.

        Every batch is padded UP to a geometric ladder of row buckets
        (`mesh.bucket_rows`) and the outputs sliced back to the valid rows —
        serving traffic with ragged batch sizes compiles one `predict`
        program per bucket instead of one per distinct tail shape (and with
        ``config["compilation_cache_dir"]`` set, those programs survive
        process restarts). `predict` is row-parallel by contract, so padding
        rows cannot influence valid rows' outputs.

        The pad/dispatch/slice mechanics live in `PredictProgram` — the same
        handle the serving plane keeps resident per model (docs/serving.md) —
        so batch transform and long-lived serving cannot drift.

        Small blocks run on one device (the reference's one-task-per-batch
        pandas_udf shape). At ``config["distributed_transform_min_rows"]`` rows
        and up, each batch is row-sharded over the full mesh with the model
        state replicated — every per-algo `predict` is a row-parallel jitted
        program, so GSPMD partitions it with zero collectives (the reference's
        all-GPU parallel transform, core.py:1531-1635)."""
        import jax

        from . import telemetry
        from .parallel.mesh import (
            default_devices,
            dtype_scope,
            ensure_compilation_cache,
            get_mesh,
        )

        ensure_compilation_cache()
        with telemetry.span(
            "transform", model=type(self).__name__, rows=int(features.shape[0])
        ), dtype_scope(
            np.float32 if self._float32_inputs else np.float64, self._matmul_precision
        ):
            n = features.shape[0]
            batch = int(config["max_records_per_batch"])
            n_dev = min(self.num_workers, len(default_devices()))
            # multi-process SPMD transforms rank-LOCAL batches: stay on local
            # devices (sharding a local batch over the global mesh would mix
            # ranks' unrelated rows and target non-addressable devices)
            use_mesh = (
                n >= int(config["distributed_transform_min_rows"])
                and n_dev > 1
                and jax.process_count() == 1
            )
            mesh = None
            if use_mesh:
                mesh = get_mesh(n_dev)
                batch *= n_dev  # per-device batch budget stays constant
            program = PredictProgram(self, cap=batch, mesh=mesh)
            if telemetry.enabled():
                reg = telemetry.registry()
                reg.inc("transform.rows", n)
                reg.inc("transform.batches", -(-n // batch) if n else 1)
            outs: List[Any] = []
            # a zero-row block still runs ONE (bucket-padded) batch: the
            # output arity/shape comes from `predict` itself, so multi-output
            # models return one correctly-shaped empty array PER output —
            # never a single bare zeros((0,)) that `_split_output` would
            # mis-map across its columns
            for start in range(0, n, batch) if n else (0,):
                stop = min(start + batch, n)
                xb = features[start:stop]
                if hasattr(xb, "todense"):
                    xb = np.asarray(xb.todense())
                result, n_valid = program.dispatch(np.asarray(xb))
                outs.append(program.fetch(result, n_valid))
            if isinstance(outs[0], tuple):
                return tuple(np.concatenate(parts, axis=0) for parts in zip(*outs))
            return np.concatenate(outs, axis=0)

    def transform(self, dataset: Any):
        pdf = as_pandas(dataset)
        extracted = self._pre_process_data(dataset, for_fit=False)
        result = self._transform_arrays(extracted.features)
        out = pdf.copy(deep=False)
        names = self._out_column_names()
        values_by_col = self._split_output(result, names, extracted)
        for name, vals in values_by_col.items():
            out[name] = vals
        return out

    def _split_output(
        self, result: Any, names: List[str], extracted: ExtractedData
    ) -> Dict[str, Any]:
        """Map raw predict output to output columns. Default: single column;
        2-D output becomes a vector column when the input was vectors
        (core.py:1577-1593 parity)."""
        name = names[0]
        if result.ndim > 1:
            if extracted.feature_kind == "vector":
                return {name: vectors_to_pandas_column(result)}
            return {name: list(result)}
        return {name: result}


# ---------------------------------------------------------------------------
# Persistence (reference core.py:253-340): metadata JSON + npz array sidecar.
# ---------------------------------------------------------------------------


def _prepare_save_path(path: str, overwrite: bool) -> None:
    """Shared exists/overwrite/mkdir preamble for every writer (incl. the
    composite writers below)."""
    if os.path.exists(path):
        if not overwrite:
            raise FileExistsError(f"Path {path} already exists; use write().overwrite().save()")
        shutil.rmtree(path)
    os.makedirs(path)


class CompositeWriter:
    """Writer for models made of OTHER models (CrossValidatorModel,
    TrainValidationSplitModel, PipelineModel): one metadata.json carrying the
    class + caller-provided fields, plus nested per-child sub-saves in each
    child's own format. One implementation so the save protocol (overwrite
    semantics, metadata shape, child layout) cannot drift between the
    composite model types.

    build_meta(instance) -> dict of extra metadata fields;
    iter_children(instance) -> iterable of (relative_subdir, child_model).
    """

    def __init__(self, instance: Any, build_meta, iter_children) -> None:
        self.instance = instance
        self._build_meta = build_meta
        self._iter_children = iter_children
        self._overwrite = False

    def overwrite(self) -> "CompositeWriter":
        self._overwrite = True
        return self

    def save(self, path: str) -> None:
        inst = self.instance
        _prepare_save_path(path, self._overwrite)
        meta = {
            "class": f"{type(inst).__module__}.{type(inst).__qualname__}",
            **self._build_meta(inst),
        }
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(meta, f, indent=2)
        for rel, child in self._iter_children(inst):
            child.write().overwrite().save(os.path.join(path, rel))


class _TpuWriter:
    def __init__(self, instance: Union[_TpuEstimator, _TpuModel]):
        self.instance = instance
        self._overwrite = False

    def overwrite(self) -> "_TpuWriter":
        self._overwrite = True
        return self

    def save(self, path: str) -> None:
        inst = self.instance
        _prepare_save_path(path, self._overwrite)
        metadata = {
            "class": f"{type(inst).__module__}.{type(inst).__qualname__}",
            "uid": inst.uid,
            "paramMap": {p.name: v for p, v in inst._paramMap.items() if _jsonable(v)},
            "defaultParamMap": {p.name: v for p, v in inst._defaultParamMap.items() if _jsonable(v)},
            "solver_params": {k: v for k, v in inst._solver_params.items() if _jsonable(v)},
            "num_workers": inst._num_workers,
            "float32_inputs": inst._float32_inputs,
            "is_model": isinstance(inst, _TpuModel),
        }
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(metadata, f, indent=2)
        if isinstance(inst, _TpuModel):
            self._write_model_attributes(inst, path)

    def _write_model_attributes(self, inst: "_TpuModel", path: str) -> None:
        """Array-serialization hook: npz bundle + JSON scalars by default;
        subclasses may use a different sidecar format (UMAP's .npy layout)."""
        arrays = {}
        scalars = {}
        for k, v in inst._model_attributes.items():
            if isinstance(v, np.ndarray):
                arrays[k] = v
            elif isinstance(v, (list, tuple)) and len(v) and isinstance(v[0], np.ndarray):
                for i, a in enumerate(v):
                    arrays[f"{k}__list{i}"] = a
                scalars[f"{k}__listlen"] = len(v)
            else:
                scalars[k] = v
        np.savez(os.path.join(path, "arrays.npz"), **arrays)
        with open(os.path.join(path, "attributes.json"), "w") as f:
            json.dump(scalars, f, default=_np_default)


def load_instance(path: str):
    """Load any saved estimator/model by the class recorded in its metadata —
    the analog of pyspark.ml's DefaultParamsReader class dispatch. Composite
    writers (CrossValidatorModel) use this to restore nested models without
    knowing their concrete type."""
    import importlib

    with open(os.path.join(path, "metadata.json")) as f:
        qualname = json.load(f)["class"]
    module, _, name = qualname.rpartition(".")
    obj: Any = importlib.import_module(module)
    for part in name.split("."):
        obj = getattr(obj, part)
    return obj.load(path)


class _TpuReader:
    def __init__(self, cls: type):
        self.cls = cls

    def load(self, path: str):
        with open(os.path.join(path, "metadata.json")) as f:
            metadata = json.load(f)
        cls = self.cls
        if metadata["is_model"]:
            attrs = self._read_model_attributes(path)
            inst = cls(**attrs)  # reference `_from_row` pattern (core.py:1150-1157)
            inst._model_attributes = attrs
        else:
            inst = cls()
        self._restore_params(inst, metadata)
        return inst

    def _read_model_attributes(self, path: str) -> Dict[str, Any]:
        """Inverse of `_TpuWriter._write_model_attributes` (hook for sidecar
        format variants)."""
        scalars: Dict[str, Any] = {}
        attrs_path = os.path.join(path, "attributes.json")
        if os.path.exists(attrs_path):
            with open(attrs_path) as f:
                scalars = json.load(f)
        arrays_path = os.path.join(path, "arrays.npz")
        attrs: Dict[str, Any] = {}
        if os.path.exists(arrays_path):
            with np.load(arrays_path, allow_pickle=False) as npz:
                attrs.update({k: npz[k] for k in npz.files})
        # reassemble list-of-array attributes
        list_lens = {k[: -len("__listlen")]: v for k, v in scalars.items() if k.endswith("__listlen")}
        for base, ln in list_lens.items():
            attrs[base] = [attrs.pop(f"{base}__list{i}") for i in range(ln)]
            scalars.pop(f"{base}__listlen")
        attrs.update(scalars)
        return attrs

    def _restore_params(self, inst: Any, metadata: Dict[str, Any]) -> None:
        for name, v in metadata["defaultParamMap"].items():
            if inst.hasParam(name):
                inst._defaultParamMap[inst.getParam(name)] = v
        for name, v in metadata["paramMap"].items():
            if inst.hasParam(name):
                inst._paramMap[inst.getParam(name)] = v
        inst._solver_params.update(metadata["solver_params"])
        inst._num_workers = metadata["num_workers"]
        inst._float32_inputs = metadata["float32_inputs"]
        inst.uid = metadata["uid"]
        return inst


def _jsonable(v: Any) -> bool:
    try:
        json.dumps(v, default=_np_default)
        return True
    except (TypeError, ValueError):
        return False


def _np_default(o: Any):
    if isinstance(o, np.generic):
        return o.item()
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"not JSON serializable: {type(o)}")
