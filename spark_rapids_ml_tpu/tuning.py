#
# Hyperparameter tuning: ParamGridBuilder, CrossValidator, CrossValidatorModel —
# drop-in for `pyspark.ml.tuning` (reference tuning.py, 177 LoC).
#
# The accelerated path mirrors the reference's meta-algorithm exactly
# (SURVEY.md §3.3): per fold, `fitMultiple` trains ALL param maps in ONE pass
# over the (device-resident) data, `_combine` packs them into one multi-model,
# and `_transform_evaluate` scores every model in ONE pass via the metrics
# sufficient-stats machinery. Estimator/evaluator combos outside that contract
# fall back to the plain fit-per-model loop (reference tuning.py:96-99 falls
# back to Spark CV the same way).
#
from __future__ import annotations

import threading
from collections import OrderedDict
from multiprocessing.pool import ThreadPool
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .core import _TpuEstimator, _TpuModel, device_dataset_scope, evaluator_label_column
from .params import Param, Params, TypeConverters
from .utils import get_logger, lockcheck


def _scoring_labels(pdf, est, eva) -> np.ndarray:
    """Held-out labels for fold scoring; the evaluator's labelCol governs
    (it may differ from the estimator's)."""
    return pdf[evaluator_label_column(est, eva)].to_numpy(dtype=np.float64)


class SweepLedger:
    """Completion ledger for one tuning sweep (docs/robustness.md "Elastic
    recovery"): each finished (fold, paramMap) fit's metric — and its model,
    for collectSubModels — is recorded keyed by the sweep's trace_id, so a
    sweep that fails mid-flight (a rank loss that exhausted the recovery
    budget, a rendezvous timeout past its retries) RESUMES at the first
    incomplete fit instead of restarting from zero. Finished fits are never
    redone; the ``sweep.fits_completed`` / ``sweep.fits_skipped`` /
    ``sweep.resumes`` counters make that assertable from telemetry alone.

    Thread-safe (folds may run on a ThreadPool). Entries live in-process for
    the duration of the sweep call; the module registry (`sweep_ledger`)
    keeps the last few ledgers around for inspection."""

    def __init__(self, trace_id: Optional[str], num_folds: int, num_models: int):
        self.trace_id = trace_id
        self.num_folds = int(num_folds)
        self.num_models = int(num_models)
        self._metrics: Dict[Tuple[int, int], float] = {}  # guarded-by: _lock
        self._models: Dict[Tuple[int, int], Any] = {}  # guarded-by: _lock
        self._lock = lockcheck.make_lock("tuning.SweepLedger._lock")

    def complete(self, fold: int, idx: int, metric: float, model: Any = None) -> None:
        from . import diagnostics, telemetry

        with self._lock:
            fresh = (fold, idx) not in self._metrics
            self._metrics[(fold, idx)] = float(metric)
            if model is not None:
                self._models[(fold, idx)] = model
        if fresh:
            telemetry.registry().inc("sweep.fits_completed")
            diagnostics.record_event(
                "sweep_fit_completed", fold=int(fold), param_map=int(idx)
            )

    def complete_fold(self, fold: int, metrics, models: Optional[List[Any]] = None) -> None:
        for j, m in enumerate(np.asarray(metrics, dtype=np.float64)):
            self.complete(fold, j, float(m), models[j] if models else None)

    def is_done(self, fold: int, idx: int) -> bool:
        with self._lock:
            return (fold, idx) in self._metrics

    def fold_done(self, fold: int) -> bool:
        with self._lock:
            return all((fold, j) in self._metrics for j in range(self.num_models))

    def metric(self, fold: int, idx: int) -> float:
        with self._lock:
            return self._metrics[(fold, idx)]

    def model(self, fold: int, idx: int) -> Any:
        with self._lock:
            return self._models.get((fold, idx))

    def fold_metrics(self, fold: int) -> np.ndarray:
        with self._lock:
            return np.asarray(
                [self._metrics[(fold, j)] for j in range(self.num_models)]
            )

    def fold_models(self, fold: int) -> Optional[List[Any]]:
        with self._lock:
            models = [self._models.get((fold, j)) for j in range(self.num_models)]
        return models if all(m is not None for m in models) else None

    def count_skipped(self, n: int) -> None:
        from . import telemetry

        if n > 0:
            telemetry.registry().inc("sweep.fits_skipped", n)

    def release_models(self) -> None:
        """Drop model references once the sweep has harvested them: the
        module registry retains the ledger (metrics) for inspection, and
        models can pin large host/device buffers for the driver's life."""
        with self._lock:
            self._models.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._metrics)


# last few sweeps' ledgers, keyed by trace_id (inspection / tests); bounded
# so long-lived drivers don't accumulate model references forever
_LEDGERS: "OrderedDict[str, SweepLedger]" = OrderedDict()
_LEDGERS_LOCK = lockcheck.make_lock("tuning._LEDGERS_LOCK")
_LEDGERS_CAP = 8


def _register_ledger(ledger: SweepLedger) -> SweepLedger:
    if ledger.trace_id is not None:
        with _LEDGERS_LOCK:
            _LEDGERS[ledger.trace_id] = ledger
            while len(_LEDGERS) > _LEDGERS_CAP:
                _LEDGERS.popitem(last=False)
    return ledger


def sweep_ledger(trace_id: str) -> Optional[SweepLedger]:
    """The completion ledger of a (recent) sweep by its trace_id."""
    with _LEDGERS_LOCK:
        return _LEDGERS.get(trace_id)


def _engine_eligible(est) -> bool:
    """Whether the device-resident multi-fit engine can run this tuning job.

    Single-controller: any `_TpuEstimator`. Under multi-process SPMD the
    engine runs too — fold masks are LOCAL row masks (each rank masks its
    own block, `FitInputs.with_row_mask` pads to the agreed local target)
    and held-out scoring allgathers the validation slices so every rank
    picks the same winner — provided the estimator supports SPMD fits at
    all, and the ingest is dense (the scoring gather is a dense-block
    control-plane allgather; sparse sweeps keep the per-fold path)."""
    from .parallel import TpuContext

    if not isinstance(est, _TpuEstimator):
        return False
    active = TpuContext.current()
    if active is None or not active.is_spmd:
        return True
    if not getattr(est, "_supports_multiprocess", False):
        return False
    sparse = (
        est.getOrDefault("enable_sparse_data_optim")
        if est.hasParam("enable_sparse_data_optim")
        else False
    )
    return not bool(sparse)


def _gather_validation(feats, labels):
    """Held-out blocks for engine scoring, globalized under multi-process
    SPMD: every rank allgathers every rank's validation slice over the
    string control plane and scores the SAME rows, so fold metrics — and
    therefore the winning param map — agree across ranks with no device
    collective. Identity in single-controller mode."""
    from .parallel import TpuContext

    active = TpuContext.current()
    if active is None or not active.is_spmd:
        return feats, labels
    from .parallel.context import allgather_ndarray

    feats = np.concatenate(
        allgather_ndarray(active.rendezvous, np.ascontiguousarray(feats)), axis=0
    )
    labels = np.concatenate(
        allgather_ndarray(active.rendezvous, np.ascontiguousarray(labels)), axis=0
    )
    return feats, labels


class ParamGridBuilder:
    """Builder for a param grid used in grid search (pyspark.ml.tuning parity)."""

    def __init__(self) -> None:
        self._param_grid: Dict[Param, List[Any]] = {}

    def addGrid(self, param: Param, values: List[Any]) -> "ParamGridBuilder":
        if not isinstance(param, Param):
            raise TypeError("param must be an instance of Param")
        self._param_grid[param] = list(values)
        return self

    def baseOn(self, *args) -> "ParamGridBuilder":
        if isinstance(args[0], dict):
            args = tuple(args[0].items())
        for param, value in args:
            self.addGrid(param, [value])
        return self

    def build(self) -> List[Dict[Param, Any]]:
        keys = list(self._param_grid.keys())
        grids: List[Dict[Param, Any]] = [{}]
        for key in keys:
            grids = [{**g, key: v} for g in grids for v in self._param_grid[key]]
        return grids


class _ValidatorParams(Params):
    numFolds = Param("numFolds", "number of folds for cross validation (>= 2)", TypeConverters.toInt)
    seed = Param("seed", "random seed for fold assignment", TypeConverters.toInt)
    parallelism = Param("parallelism", "number of threads evaluating folds in parallel", TypeConverters.toInt)
    collectSubModels = Param("collectSubModels", "whether to keep all sub-models", TypeConverters.toBoolean)
    foldCol = Param("foldCol", "optional column with user-specified fold ids", TypeConverters.toString)

    def __init__(self) -> None:
        super().__init__()
        self._estimator: Optional[Any] = None
        self._estimatorParamMaps: Optional[List[Dict[Param, Any]]] = None
        self._evaluator: Optional[Any] = None
        self._setDefault(numFolds=3, seed=0, parallelism=1, collectSubModels=False, foldCol="")

    def getEstimator(self):
        return self._estimator

    def setEstimator(self, value):
        self._estimator = value
        return self

    def getEstimatorParamMaps(self):
        return self._estimatorParamMaps

    def setEstimatorParamMaps(self, value):
        self._estimatorParamMaps = value
        return self

    def getEvaluator(self):
        return self._evaluator

    def setEvaluator(self, value):
        self._evaluator = value
        return self

    def getNumFolds(self) -> int:
        return self.getOrDefault("numFolds")

    def setNumFolds(self, value: int):
        return self._set(numFolds=value)

    def setSeed(self, value: int):
        return self._set(seed=value)

    def setParallelism(self, value: int):
        return self._set(parallelism=value)


class CrossValidator(_ValidatorParams):
    """K-fold cross validation over a param grid.

    >>> cv = CrossValidator(estimator=lr, estimatorParamMaps=grid, evaluator=ev)
    >>> cv_model = cv.fit(df)
    """

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        for name in ("estimator", "estimatorParamMaps", "evaluator"):
            if name in kwargs:
                getattr(self, f"set{name[0].upper()}{name[1:]}")(kwargs.pop(name))
        self._set(**kwargs)

    def _kfold_indices(self, n: int, pdf) -> List[Tuple[np.ndarray, np.ndarray]]:
        num_folds = self.getNumFolds()
        fold_col = self.getOrDefault("foldCol")
        if fold_col:
            fold_ids = pdf[fold_col].to_numpy(dtype=int)
            if (fold_ids < 0).any() or (fold_ids >= num_folds).any():
                raise ValueError(f"foldCol values must be in [0, {num_folds})")
        else:
            # balanced permutation split: every fold is guaranteed non-empty
            # for n >= numFolds (a uniform random draw is not)
            if n < num_folds:
                raise ValueError(f"dataset has {n} rows but numFolds={num_folds}")
            rng = np.random.default_rng(self.getOrDefault("seed"))
            fold_ids = rng.permutation(n) % num_folds
        out = []
        for f in range(num_folds):
            mask = fold_ids == f
            train_idx, valid_idx = np.nonzero(~mask)[0], np.nonzero(mask)[0]
            if len(train_idx) == 0 or len(valid_idx) == 0:
                raise ValueError(f"fold {f} is empty; check foldCol values")
            out.append((train_idx, valid_idx))
        return out

    def fit(self, dataset: Any) -> "CrossValidatorModel":
        # one trace for the WHOLE cross-validation: every fold fit, held-out
        # scoring transform, and the best-model refit share this trace_id
        # (inner fit scopes adopt it, each with its own fit_id), so the
        # per-rank JSONL merges into ONE Perfetto timeline. The active
        # TpuContext is passed so an SPMD cv.fit (all ranks enter in
        # lockstep) propagates rank 0's id instead of minting per rank.
        from . import diagnostics
        from .parallel import TpuContext

        with diagnostics.trace_scope(type(self).__name__, TpuContext.current()):
            return self._fit_traced(dataset)

    def _fit_traced(self, dataset: Any) -> "CrossValidatorModel":
        from .data import as_pandas

        est = self.getEstimator()
        epm = self.getEstimatorParamMaps()
        eva = self.getEvaluator()
        if est is None or epm is None or eva is None:
            raise ValueError("estimator, estimatorParamMaps and evaluator must all be set")
        logger = get_logger(type(self))

        pdf = as_pandas(dataset)
        n = len(pdf)
        folds = self._kfold_indices(n, pdf)
        num_models = len(epm)
        metrics = np.zeros((len(folds), num_models))
        accelerated = isinstance(est, _TpuEstimator) and est._supportsTransformEvaluate(eva)
        engine = accelerated and _engine_eligible(est)
        logger.info(
            "CrossValidator: %d folds x %d param maps (%s path)",
            len(folds), num_models,
            "device-resident engine" if engine
            else ("fused single-pass" if accelerated else "fallback per-model"),
        )

        collect_sub = bool(self.getOrDefault("collectSubModels"))
        sub_models: Optional[List[List[Any]]] = [None] * len(folds) if collect_sub else None
        parallelism = min(self.getOrDefault("parallelism"), len(folds))

        # Sweep completion ledger (docs/robustness.md "Elastic recovery"):
        # every finished (fold, paramMap) fit is recorded keyed by this
        # sweep's trace_id. A mid-flight control-plane failure that escapes
        # the per-fit recovery machinery resumes the sweep at the first
        # incomplete fit — bounded by config["sweep_max_resumes"] — and
        # finished fits are NEVER redone (sweep.fits_skipped counts the
        # ledger-served ones).
        from . import diagnostics
        from .core import config
        from .errors import RankFailedError, RendezvousTimeoutError

        tr = diagnostics.current_trace()
        ledger = _register_ledger(
            SweepLedger(tr.get("trace_id") if tr else None, len(folds), num_models)
        )

        def run_folds(run_fold) -> None:
            max_resumes = max(0, int(config.get("sweep_max_resumes", 1)))

            def guarded(i):
                if ledger.fold_done(i):
                    # completed before the failure: serve from the ledger
                    ledger.count_skipped(num_models)
                    if collect_sub and sub_models[i] is None:
                        sub_models[i] = ledger.fold_models(i)
                    return ledger.fold_metrics(i)
                return run_fold(i)

            for attempt in range(max_resumes + 1):
                try:
                    if parallelism > 1:
                        with ThreadPool(parallelism) as pool:
                            for i, scores in enumerate(pool.map(guarded, range(len(folds)))):
                                metrics[i] = scores
                    else:
                        for i in range(len(folds)):
                            metrics[i] = guarded(i)
                    return
                except (RankFailedError, RendezvousTimeoutError) as e:
                    if attempt >= max_resumes:
                        raise
                    from . import telemetry

                    telemetry.registry().inc("sweep.resumes")
                    diagnostics.record_event(
                        "sweep_resume", completed=len(ledger),
                        error=type(e).__name__,
                    )
                    logger.warning(
                        "sweep failed mid-flight (%s: %s); resuming at the "
                        "first incomplete fit — %d/%d (fold, paramMap) fits "
                        "already complete and ledger-served",
                        type(e).__name__, e, len(ledger), len(folds) * num_models,
                    )

        def pick_best():
            avg = metrics.mean(axis=0)
            std = metrics.std(axis=0)
            best_idx = int(np.argmax(avg) if eva.isLargerBetter() else np.argmin(avg))
            logger.info(
                "CrossValidator: best param map %d (avg metric %.6f)", best_idx, avg[best_idx]
            )
            return avg, std, best_idx

        if engine:
            # Multi-fit engine: the FULL dataset is ingested and laid out in
            # HBM exactly once; each fold is realized as a row-weight mask
            # over that one placement (w_fold = w * mask — the solvers treat
            # w == 0 rows as padding), every fold's param maps dispatch
            # through the batched-sweep solver where eligible, held-out
            # scoring SLICES the one ingested host block, and the final
            # best-model refit reuses the placement once more. numFolds x
            # paramMaps fits -> 1 ingest + 1 layout (telemetry-asserted in
            # tests/test_multifit.py).
            labels = _scoring_labels(pdf, est, eva)
            if parallelism > 1:
                # every fold solves on the SAME mesh over the SAME placed
                # dataset — the accelerator is the bottleneck, so driver-side
                # thread parallelism adds only dispatch contention (and
                # concurrent sharded executions over shared buffers can
                # deadlock XLA CPU collectives); folds run sequentially here
                logger.info(
                    "CrossValidator: ignoring parallelism=%d on the "
                    "device-resident engine (folds share one mesh placement)",
                    parallelism,
                )
                parallelism = 1
            with device_dataset_scope() as scope:

                def run_fold(fold_i: int) -> np.ndarray:
                    train_idx, valid_idx = folds[fold_i]
                    mask = np.zeros(n)
                    mask[train_idx] = 1.0
                    models = est._fit_internal(pdf, list(epm), row_mask=mask)
                    if collect_sub:
                        sub_models[fold_i] = models
                    combined = models[0]._combine(models)
                    feats, yv = _gather_validation(
                        scope.last.extracted.features[valid_idx], labels[valid_idx]
                    )
                    scores = np.asarray(
                        combined._transform_evaluate_arrays(feats, yv, eva)
                    )
                    ledger.complete_fold(fold_i, scores, models if collect_sub else None)
                    return scores

                run_folds(run_fold)
                avg, std, best_idx = pick_best()
                best_model = est.copy(epm[best_idx]).fit(pdf)  # reuses the placement
            ledger.release_models()
            return CrossValidatorModel(
                bestModel=best_model, avgMetrics=list(avg), stdMetrics=list(std),
                subModels=sub_models,
            )

        def run_fold(fold_i: int) -> np.ndarray:
            train_idx, valid_idx = folds[fold_i]
            train = pdf.iloc[train_idx].reset_index(drop=True)
            valid = pdf.iloc[valid_idx].reset_index(drop=True)
            if accelerated:
                # ONE fit pass for all param maps, ONE eval pass for all models
                models = [m for _, m in sorted(est.fitMultiple(train, epm))]
                if collect_sub:
                    sub_models[fold_i] = models
                combined = models[0]._combine(models)
                scores = np.asarray(combined._transform_evaluate(valid, eva))
                ledger.complete_fold(fold_i, scores, models if collect_sub else None)
                return scores
            scores = []
            fold_models = []
            for j, pm in enumerate(epm):
                # (fold, paramMap) granularity on the per-model path: a
                # resume after a mid-fold failure redoes only the maps that
                # never finished
                if ledger.is_done(fold_i, j):
                    ledger.count_skipped(1)
                    fold_models.append(ledger.model(fold_i, j))
                    scores.append(ledger.metric(fold_i, j))
                    continue
                model = est.copy(pm).fit(train)
                score = float(eva.evaluate(model.transform(valid)))
                ledger.complete(fold_i, j, score, model if collect_sub else None)
                fold_models.append(model)
                scores.append(score)
            if collect_sub:
                sub_models[fold_i] = fold_models
            return np.asarray(scores)

        run_folds(run_fold)
        avg, std, best_idx = pick_best()
        best_model = est.copy(epm[best_idx]).fit(pdf)
        ledger.release_models()
        return CrossValidatorModel(
            bestModel=best_model, avgMetrics=list(avg), stdMetrics=list(std), subModels=sub_models
        )


class CrossValidatorModel(Params):
    def __init__(self, bestModel=None, avgMetrics=None, stdMetrics=None, subModels=None) -> None:
        super().__init__()
        self.bestModel = bestModel
        self.avgMetrics = avgMetrics or []
        self.stdMetrics = stdMetrics or []
        self.subModels = subModels

    def transform(self, dataset: Any):
        return self.bestModel.transform(dataset)

    # persistence: a composite directory — top-level metadata (metrics) plus
    # nested per-model saves in each model's own format, restored by class
    # dispatch (the shared CompositeWriter protocol). The reference
    # round-trips CV models through pyspark's CrossValidatorModel writer
    # (reference tuning.py:139-177).
    def write(self):
        from .core import CompositeWriter

        if self.bestModel is None:
            raise ValueError("CrossValidatorModel has no bestModel to save")

        def children(inst):
            yield "bestModel", inst.bestModel
            for i, fold_models in enumerate(inst.subModels or ()):
                for j, m in enumerate(fold_models):
                    yield f"subModels/fold{i}/model{j}", m

        return CompositeWriter(
            self,
            build_meta=lambda inst: {
                "avgMetrics": [float(v) for v in inst.avgMetrics],
                "stdMetrics": [float(v) for v in inst.stdMetrics],
                "numSubModelFolds": len(inst.subModels) if inst.subModels else 0,
                "numSubModelsPerFold": len(inst.subModels[0]) if inst.subModels else 0,
            },
            iter_children=children,
        )

    def save(self, path: str) -> None:
        self.write().save(path)

    @classmethod
    def load(cls, path: str) -> "CrossValidatorModel":
        import json
        import os

        from .core import load_instance

        with open(os.path.join(path, "metadata.json")) as f:
            meta = json.load(f)
        best = load_instance(os.path.join(path, "bestModel"))
        sub = None
        if meta.get("numSubModelFolds"):
            sub = [
                [
                    load_instance(os.path.join(path, "subModels", f"fold{i}", f"model{j}"))
                    for j in range(meta["numSubModelsPerFold"])
                ]
                for i in range(meta["numSubModelFolds"])
            ]
        return cls(
            bestModel=best,
            avgMetrics=meta["avgMetrics"],
            stdMetrics=meta["stdMetrics"],
            subModels=sub,
        )


class TrainValidationSplit(_ValidatorParams):
    """Single train/validation split over a param grid — the other member of
    pyspark.ml.tuning (the reference leaves it to pyspark; outside Spark that
    class cannot drive these estimators, so the framework carries it). Uses
    the same fused fitMultiple + _combine + _transform_evaluate path as
    CrossValidator when the estimator supports it.

    >>> tvs = TrainValidationSplit(estimator=lr, estimatorParamMaps=grid,
    ...                            evaluator=ev, trainRatio=0.75)
    >>> model = tvs.fit(df)
    """

    trainRatio = Param("trainRatio", "fraction of rows used for training (rest validates)", TypeConverters.toFloat)

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(trainRatio=0.75)
        # fold-specific inherited params have no meaning for a single split —
        # drop them so explainParams doesn't advertise dead knobs
        for dead in ("numFolds", "foldCol"):
            self._defaultParamMap.pop(self.getParam(dead), None)
        for name in ("estimator", "estimatorParamMaps", "evaluator"):
            if name in kwargs:
                getattr(self, f"set{name[0].upper()}{name[1:]}")(kwargs.pop(name))
        self._set(**kwargs)

    def explainParams(self) -> str:
        # hide the fold-specific inherited params (dead knobs for a single
        # split); they must stay resolvable internally for the base __init__
        return "\n".join(
            self.explainParam(p)
            for p in self.params
            if p.name not in ("numFolds", "foldCol")
        )

    def setTrainRatio(self, value: float) -> "TrainValidationSplit":
        return self._set(trainRatio=value)

    def getTrainRatio(self) -> float:
        return self.getOrDefault("trainRatio")

    def fit(self, dataset: Any) -> "TrainValidationSplitModel":
        # one trace per sweep (see CrossValidator.fit)
        from . import diagnostics
        from .parallel import TpuContext

        with diagnostics.trace_scope(type(self).__name__, TpuContext.current()):
            return self._fit_traced(dataset)

    def _fit_traced(self, dataset: Any) -> "TrainValidationSplitModel":
        from .data import as_pandas

        est = self.getEstimator()
        epm = self.getEstimatorParamMaps()
        eva = self.getEvaluator()
        if est is None or epm is None or eva is None:
            raise ValueError("estimator, estimatorParamMaps and evaluator must all be set")
        ratio = float(self.getOrDefault("trainRatio"))
        if not 0.0 < ratio < 1.0:
            raise ValueError(f"trainRatio must be in (0, 1), got {ratio}")
        logger = get_logger(type(self))

        pdf = as_pandas(dataset)
        n = len(pdf)
        rng = np.random.default_rng(self.getOrDefault("seed"))
        perm = rng.permutation(n)
        n_train = int(round(ratio * n))
        if n_train == 0 or n_train == n:
            raise ValueError(f"trainRatio={ratio} leaves an empty split for {n} rows")
        train = pdf.iloc[perm[:n_train]].reset_index(drop=True)
        valid = pdf.iloc[perm[n_train:]].reset_index(drop=True)

        accelerated = isinstance(est, _TpuEstimator) and est._supportsTransformEvaluate(eva)
        engine = accelerated and _engine_eligible(est)
        logger.info(
            "TrainValidationSplit: %d train / %d valid x %d param maps (%s path)",
            n_train, n - n_train, len(epm),
            "device-resident engine" if engine
            else ("fused single-pass" if accelerated else "fallback per-model"),
        )

        # Sweep completion ledger — the same elastic-recovery contract as
        # CrossValidator (docs/robustness.md "Elastic recovery"), with one
        # "fold": a mid-flight control-plane failure resumes at the first
        # incomplete param-map fit, finished fits ledger-served, bounded by
        # config["sweep_max_resumes"].
        from . import diagnostics
        from .core import config
        from .errors import RankFailedError, RendezvousTimeoutError

        collect_sub = bool(self.getOrDefault("collectSubModels"))
        tr = diagnostics.current_trace()
        ledger = _register_ledger(
            SweepLedger(tr.get("trace_id") if tr else None, 1, len(epm))
        )

        def with_resume(run_once):
            max_resumes = max(0, int(config.get("sweep_max_resumes", 1)))
            for attempt in range(max_resumes + 1):
                try:
                    return run_once()
                except (RankFailedError, RendezvousTimeoutError) as e:
                    if attempt >= max_resumes:
                        raise
                    from . import telemetry

                    telemetry.registry().inc("sweep.resumes")
                    diagnostics.record_event(
                        "sweep_resume", completed=len(ledger),
                        error=type(e).__name__,
                    )
                    logger.warning(
                        "sweep failed mid-flight (%s: %s); resuming at the "
                        "first incomplete fit — %d/%d param-map fits already "
                        "complete and ledger-served",
                        type(e).__name__, e, len(ledger), len(epm),
                    )
            raise AssertionError("unreachable")  # pragma: no cover

        if engine:
            # same multi-fit engine as CrossValidator, with one fold: one
            # placement serves the masked grid fit, the sliced held-out
            # scoring, AND the final full-data refit
            mask = np.zeros(n)
            mask[perm[:n_train]] = 1.0
            labels = _scoring_labels(pdf, est, eva)
            valid_idx = perm[n_train:]
            with device_dataset_scope() as scope:

                def run_grid():
                    if ledger.fold_done(0):
                        ledger.count_skipped(len(epm))
                        return ledger.fold_metrics(0), (
                            ledger.fold_models(0) if collect_sub else None
                        )
                    models = est._fit_internal(pdf, list(epm), row_mask=mask)
                    combined = models[0]._combine(models)
                    feats, yv = _gather_validation(
                        scope.last.extracted.features[valid_idx], labels[valid_idx]
                    )
                    metrics = np.asarray(
                        combined._transform_evaluate_arrays(feats, yv, eva)
                    )
                    ledger.complete_fold(0, metrics, models if collect_sub else None)
                    return metrics, models

                metrics, models = with_resume(run_grid)
                best_idx = int(np.argmax(metrics) if eva.isLargerBetter() else np.argmin(metrics))
                logger.info(
                    "TrainValidationSplit: best param map %d (metric %.6f)",
                    best_idx, metrics[best_idx],
                )
                best_model = est.copy(epm[best_idx]).fit(pdf)  # reuses the placement
            ledger.release_models()
            sub = list(models) if collect_sub and models is not None else None
            return TrainValidationSplitModel(
                bestModel=best_model, validationMetrics=list(metrics), subModels=sub
            )
        if accelerated:

            def run_grid():
                if ledger.fold_done(0):
                    ledger.count_skipped(len(epm))
                    return ledger.fold_metrics(0), (
                        ledger.fold_models(0) if collect_sub else None
                    )
                models = [m for _, m in sorted(est.fitMultiple(train, epm))]
                combined = models[0]._combine(models)
                metrics = np.asarray(combined._transform_evaluate(valid, eva))
                ledger.complete_fold(0, metrics, models if collect_sub else None)
                return metrics, models

            metrics, models = with_resume(run_grid)
        else:
            # parallelism spans PARAM MAPS here (pyspark TVS semantics; CV
            # parallelizes over folds instead); (paramMap) granularity on
            # this path — a resume redoes only the maps that never finished
            par = min(int(self.getOrDefault("parallelism")), len(epm))

            def fit_score_one(j_pm):
                j, pm = j_pm
                if ledger.is_done(0, j):
                    ledger.count_skipped(1)
                    return ledger.metric(0, j), ledger.model(0, j)
                model = est.copy(pm).fit(train)
                score = float(eva.evaluate(model.transform(valid)))
                ledger.complete(0, j, score, model if collect_sub else None)
                return score, model

            def run_grid():
                if par > 1:
                    with ThreadPool(par) as pool:
                        out = pool.map(fit_score_one, list(enumerate(epm)))
                else:
                    out = [fit_score_one(j_pm) for j_pm in enumerate(epm)]
                return np.asarray([s for s, _ in out]), [m for _, m in out]

            metrics, models = with_resume(run_grid)

        best_idx = int(np.argmax(metrics) if eva.isLargerBetter() else np.argmin(metrics))
        logger.info("TrainValidationSplit: best param map %d (metric %.6f)", best_idx, metrics[best_idx])
        best_model = est.copy(epm[best_idx]).fit(pdf)
        ledger.release_models()
        sub = list(models) if collect_sub and models is not None else None
        return TrainValidationSplitModel(
            bestModel=best_model, validationMetrics=list(metrics), subModels=sub
        )


class TrainValidationSplitModel(Params):
    def __init__(self, bestModel=None, validationMetrics=None, subModels=None) -> None:
        super().__init__()
        self.bestModel = bestModel
        self.validationMetrics = validationMetrics or []
        self.subModels = subModels

    def transform(self, dataset: Any):
        return self.bestModel.transform(dataset)

    def write(self):
        from .core import CompositeWriter

        if self.bestModel is None:
            raise ValueError("TrainValidationSplitModel has no bestModel to save")

        def children(inst):
            yield "bestModel", inst.bestModel
            for j, m in enumerate(inst.subModels or ()):
                yield f"subModels/model{j}", m

        return CompositeWriter(
            self,
            build_meta=lambda inst: {
                "validationMetrics": [float(v) for v in inst.validationMetrics],
                "numSubModels": len(inst.subModels) if inst.subModels else 0,
            },
            iter_children=children,
        )

    def save(self, path: str) -> None:
        self.write().save(path)

    @classmethod
    def load(cls, path: str) -> "TrainValidationSplitModel":
        import json
        import os

        from .core import load_instance

        with open(os.path.join(path, "metadata.json")) as f:
            meta = json.load(f)
        best = load_instance(os.path.join(path, "bestModel"))
        sub = None
        if meta.get("numSubModels"):
            sub = [
                load_instance(os.path.join(path, "subModels", f"model{j}"))
                for j in range(meta["numSubModels"])
            ]
        return cls(bestModel=best, validationMetrics=meta["validationMetrics"], subModels=sub)
