#
# Solver checkpoints: collective-consistent, periodically host-fetched solver
# state, so a fit interrupted by a transient fault (or a rank loss) resumes
# from the last checkpoint instead of from scratch (docs/robustness.md
# "Elastic recovery").
#
# Design:
#   * A `CheckpointStore` lives for the dynamic extent of ONE recoverable fit
#     stage (`core.recoverable_stage` / `core.retryable_stage` install it via
#     `ensure_scope`). Attempts within the stage — bounded transient retries
#     AND recovery epochs after a rank loss — share the store; the stage's
#     exit clears it, so checkpoints never leak across fits.
#   * Checkpoints are HOST-fetched numpy state (that is the point: device
#     state dies with the mesh; host copies survive a re-mesh). Each carries
#     a `placement_key` naming the mesh/shape it was taken on:
#       - same placement  -> EXACT resume (bit-identical to an uninterrupted
#         fit — the state round-trips device -> host -> device losslessly);
#       - different placement (degraded survivor mesh) -> the solver falls
#         back to its PORTABLE subset (k-means centers, the GLM iterate,
#         sufficient statistics), deterministic given the survivor set.
#   * Cadence is `config["checkpoint_every_iters"]` (0 disables — the
#     default: no host fetch is ever added to an un-checkpointed fit).
#
# The k-means host loop checkpoints its centers (the shift scalar is fetched
# each iteration anyway, so the cadence fetch is near-free); the GLM / OWL-QN
# solvers segment their one big `lax.while_loop` into host segments of
# `checkpoint_every_iters` inner iterations via `run_segmented_while`; the
# linear/PCA family retains its one-pass sufficient statistics through
# `CheckpointStore.get_or_compute`.
#
from __future__ import annotations

import contextlib
import contextvars
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from .utils import lockcheck

__all__ = [
    "SolverCheckpoint",
    "CheckpointStore",
    "checkpoint_scope",
    "ensure_scope",
    "active_store",
    "every_iters",
    "solver_checkpoints_active",
    "placement_key_of",
    "run_segmented_while",
]


@dataclass
class SolverCheckpoint:
    """One host-fetched solver snapshot.

    `state` maps names to host numpy arrays / scalars. `placement_key`
    identifies the mesh + data layout the snapshot was taken on (exact-resume
    eligibility); `portable` optionally carries the mesh-independent subset
    a degraded-mesh resume may warm-start from."""

    solver: str
    iteration: int
    state: Dict[str, Any]
    placement_key: Optional[tuple] = None
    portable: Dict[str, Any] = field(default_factory=dict)
    wall_t: float = field(default_factory=time.time)


class CheckpointStore:
    """Keyed checkpoint container for one recoverable fit stage.

    Thread-safe (fold fits may run on pool threads inside one scope). Saves
    and restores are counted through the telemetry registry
    (``checkpoint.saves`` / ``checkpoint.restores`` /
    ``checkpoint.stats_reuses``) so the elastic-recovery acceptance tests can
    assert resume-from-checkpoint rather than re-solve-from-scratch."""

    def __init__(self) -> None:
        self._entries: Dict[str, SolverCheckpoint] = {}  # guarded-by: _lock
        self._lock = lockcheck.make_lock("checkpoint.CheckpointStore._lock")

    def save(self, key: str, ckpt: SolverCheckpoint) -> None:
        from . import diagnostics, telemetry

        with self._lock:
            self._entries[key] = ckpt
        telemetry.registry().inc("checkpoint.saves")
        diagnostics.record_event(
            "checkpoint_saved", solver=ckpt.solver, iteration=ckpt.iteration, key=key
        )

    def load(self, key: str) -> Optional[SolverCheckpoint]:
        with self._lock:
            ckpt = self._entries.get(key)
        if ckpt is not None:
            from . import diagnostics, telemetry

            telemetry.registry().inc("checkpoint.restores")
            diagnostics.record_event(
                "checkpoint_restored", solver=ckpt.solver, iteration=ckpt.iteration,
                key=key,
            )
        return ckpt

    def peek(self, key: str) -> Optional[SolverCheckpoint]:
        """`load` without counting a restore (cadence bookkeeping)."""
        with self._lock:
            return self._entries.get(key)

    def get_or_compute(self, key: str, fn: Callable[[], Dict[str, Any]],
                       *, solver: str, placement_key: Optional[tuple] = None) -> Dict[str, Any]:
        """Host-retained sufficient statistics: return the stored state when
        the key AND placement match (a transient retry / same-mesh re-solve
        skips the data pass entirely), else compute, retain, and return."""
        from . import telemetry

        with self._lock:
            ckpt = self._entries.get(key)
        if ckpt is not None and ckpt.placement_key == placement_key:
            telemetry.registry().inc("checkpoint.stats_reuses")
            return ckpt.state
        state = fn()
        self.save(key, SolverCheckpoint(
            solver=solver, iteration=0, state=state, placement_key=placement_key
        ))
        return state

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


# Context-local (same isolation argument as core's DeviceDataset scope):
# concurrent fits on different threads must not share checkpoint state.
_STORE: "contextvars.ContextVar[Optional[CheckpointStore]]" = contextvars.ContextVar(
    "srml_checkpoint_store", default=None
)


def active_store() -> Optional[CheckpointStore]:
    """The store installed by the enclosing recoverable/retryable stage, or
    None (solvers then skip all checkpoint work)."""
    return _STORE.get()


@contextlib.contextmanager
def checkpoint_scope(store: Optional[CheckpointStore] = None):
    """Install a fresh (or given) CheckpointStore for the dynamic extent;
    clears it on exit (checkpoints are per-stage, never cross-fit)."""
    own = store is None
    scope = CheckpointStore() if own else store
    token = _STORE.set(scope)
    try:
        yield scope
    finally:
        _STORE.reset(token)
        if own:
            scope.clear()


@contextlib.contextmanager
def ensure_scope():
    """`checkpoint_scope` that ADOPTS an already-active store (the outer
    recoverable stage owns clearing) instead of shadowing it — so
    `recoverable_stage`'s store survives the nested `retryable_stage`."""
    existing = _STORE.get()
    if existing is not None:
        yield existing
        return
    with checkpoint_scope() as scope:
        yield scope


def every_iters() -> int:
    """``config["checkpoint_every_iters"]``: solver-checkpoint cadence in
    inner iterations; 0 disables (the default)."""
    from .core import config

    try:
        return max(0, int(config.get("checkpoint_every_iters", 0)))
    except (TypeError, ValueError):
        return 0


def solver_checkpoints_active() -> bool:
    """Whether solvers should checkpoint: a cadence is configured AND a
    store is installed by the enclosing stage."""
    return every_iters() > 0 and _STORE.get() is not None


def placement_key_of(inputs: Any) -> tuple:
    """Placement identity of a `core.FitInputs`: (mesh device ids, global
    valid rows, columns, dtype). Checkpoints taken under one placement
    exact-resume only under an EQUAL key; a reformed survivor mesh changes
    the device set, so stale full-state snapshots are rejected and the
    solver falls back to its portable subset."""
    mesh = getattr(inputs, "mesh", None)
    devs = (
        tuple(int(d.id) for d in mesh.devices.flatten()) if mesh is not None else ()
    )
    return (
        devs,
        int(getattr(inputs, "n_valid", 0)),
        int(getattr(inputs, "n_cols", 0)),
        str(getattr(inputs, "dtype", "")),
    )


# ------------------------------------------------------------------------
# Segmented while_loop driver: the GLM / OWL-QN checkpointing substrate.
# ------------------------------------------------------------------------


def run_segmented_while(
    cond: Callable,
    body: Callable,
    state0: Any,
    *,
    it_of: Callable[[Any], Any],
    every: int,
    store: Optional[CheckpointStore],
    key: str,
    solver: str,
    placement_key: Optional[tuple] = None,
    max_iter: int,
    portable_of: Optional[Callable[[Any], Dict[str, Any]]] = None,
) -> Any:
    """Run ``while cond(state): state = body(state)`` as HOST segments of
    ``every`` inner iterations, checkpointing the full state at each segment
    boundary.

    The segment itself is one jitted ``lax.while_loop`` whose condition is
    ``cond(state) AND it < seg_end`` — inside a segment nothing changes
    versus the monolithic loop, and the boundary fetch round-trips the state
    through host numpy losslessly, so a resume ON THE SAME MESH is
    bit-identical to an uninterrupted (checkpointed) run. On restore, every
    leaf's shape/dtype is validated against `state0`; any mismatch (a
    degraded mesh changed the data-dependent leaves) discards the snapshot —
    callers wanting a portable warm start rebuild `state0` from the
    checkpoint's `portable` payload first.

    `it_of(state)` extracts the iteration counter (used for the segment
    bound and the checkpoint's `iteration` field). `portable_of(state)`
    optionally extracts the mesh-independent subset stored alongside the
    full leaves — what a degraded-mesh resume warm-starts from."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    leaves0, treedef = jax.tree_util.tree_flatten(state0)
    state = state0
    if store is not None:
        ckpt = store.peek(key)
        if ckpt is not None and ckpt.placement_key == placement_key:
            saved = ckpt.state.get("leaves")
            if (
                isinstance(saved, list)
                and len(saved) == len(leaves0)
                and all(
                    tuple(np.shape(s)) == tuple(np.shape(t))
                    for s, t in zip(saved, leaves0)
                )
            ):
                state = jax.tree_util.tree_unflatten(
                    treedef,
                    [
                        jnp.asarray(s, dtype=jnp.asarray(t).dtype)
                        for s, t in zip(saved, leaves0)
                    ],
                )
                store.load(key)  # count the restore + flight-recorder event

    cond_j = jax.jit(cond)

    def _segment(st, seg_end):
        return jax.lax.while_loop(
            lambda s: jnp.logical_and(cond(s), it_of(s) < seg_end), body, st
        )

    seg_j = jax.jit(_segment)
    from . import telemetry
    from .parallel import chaos
    from .utils import numcheck

    # runtime numerics sanitizer (SRML_NUMCHECK=1): resolved once per loop;
    # sweeps the checkpoint's already-host-fetched leaves at each boundary
    _nc = numcheck.hook()

    while bool(cond_j(state)):  # host-fetch-ok: one probe per checkpoint SEGMENT (every_iters inner iterations), not per solver step
        it_now = int(np.asarray(it_of(state)))  # host-fetch-ok: segment-boundary counter read, cadence-bounded
        seg_end = min(it_now + max(1, every), max_iter)
        state = seg_j(state, jnp.asarray(seg_end, jnp.int32))
        if store is not None:
            # the leaf fetch below is the segment's device sync — the
            # efficiency attributor times it as `execute` (the wait IS the
            # remaining device work of this segment), and the host-side
            # checkpoint serialization as `host`; no sync is added
            with telemetry.device_wait("segment"):
                leaves = [np.asarray(l) for l in jax.tree_util.tree_leaves(state)]
                it_after = int(np.asarray(it_of(state)))  # host-fetch-ok: the checkpoint itself — state must land on host to survive the process
            if _nc is not None:
                # a NaN leaf here would poison every later resume of this
                # trajectory; the bytes are already on host. allow_inf: the
                # GLM/CD states carry deliberate `jnp.inf` sentinels
                # (best-loss initializers, padding)
                _nc(f"segment.{solver}", solver=solver, iteration=it_after,
                    allow_inf=True,
                    **{f"leaf{i}": lv for i, lv in enumerate(leaves)})
            with telemetry.host_section("segment"):
                store.save(key, SolverCheckpoint(
                    solver=solver, iteration=it_after,
                    state={"leaves": leaves}, placement_key=placement_key,
                    portable=portable_of(state) if portable_of is not None else {},
                ))
            # mid-solve fault injection point: a `fail:stage=solve` plan
            # entry interrupts AFTER this boundary's checkpoint landed, so
            # the bounded retry exercises the real resume-from-checkpoint
            # path instead of restarting the whole loop
            chaos.maybe_fail_stage("solve", it_after)
            # cooperative scheduler preemption (docs/scheduling.md): same
            # placement in the ladder as the chaos hooks — the boundary
            # checkpoint is down, so yielding here loses zero work and the
            # resumed job is bit-identical to an uninterrupted segmented run
            from .scheduler.context import preemption_point

            preemption_point(solver, it_after)
        if seg_end >= max_iter:
            break
    return state
