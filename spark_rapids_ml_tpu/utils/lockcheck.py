#
# Runtime lock-order sanitizer: the dynamic twin of the static `lock-order`
# / `blocking-under-lock` analysis (ci/analysis/rules/concurrency.py). The
# static pass PROPOSES the acquisition-order graph from source; this module
# VALIDATES it under real contention at test time, lockdep-style
# (docs/robustness.md "Threading model").
#
# Opt-in via ``SRML_LOCKCHECK=1`` (resolved when each lock is CONSTRUCTED —
# the CI lanes export it before pytest imports the framework). Disabled,
# `make_lock`/`make_condition` return the plain `threading` primitive: zero
# wrapper, zero overhead, pinned by tests/test_lockcheck.py.
#
# Enabled, every framework lock built through `make_lock(name, kind)` is a
# `CheckedLock` that on each acquisition records, per thread, the stack of
# locks already held and feeds a process-global observed-order graph:
#
#   * edge A -> B the first time B is acquired while A is held;
#   * acquiring B while holding A when the REVERSE edge B -> A was observed
#     earlier is an ORDER INVERSION — the two code paths can deadlock under
#     the right interleaving even if this run got lucky. The violation is
#     recorded here AND as a `lockcheck.inversion` flight-recorder event
#     (post-mortem timelines interleave it with the hang it predicts);
#   * re-entrant re-acquisition of the same named lock adds no edge — an
#     RLock taking itself twice is the sanctioned pattern, not an inversion;
#   * a hold longer than ``config["lockcheck_long_hold_ms"]`` (seeded from
#     SRML_LOCKCHECK_LONG_HOLD_MS, default 500 ms) records a
#     `lockcheck.long_hold` violation with the per-lock high-watermark —
#     the runtime face of blocking-under-lock.
#
# Lock NAMES use the static pass's ids (`<module>.<Class>.<attr>` /
# `<module>.<GLOBAL>`), so a static cycle finding and a runtime inversion
# report point at the same vocabulary.
#
# ``SRML_LOCKCHECK_REPORT=<path>`` writes the violation report at interpreter
# exit — the artifact ci/test.sh archives next to the analysis verdict.
#
from __future__ import annotations

import atexit
import json
import os
import threading
import time
import traceback
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "enabled",
    "make_lock",
    "make_condition",
    "CheckedLock",
    "violations",
    "edges",
    "report",
    "write_report",
    "reset",
    "snapshot",
    "restore",
    "long_hold_threshold_s",
]

_DEFAULT_LONG_HOLD_MS = 500.0


def enabled() -> bool:
    """Sanitizer opt-in, read per call so tests can flip it; production
    locks resolve it once, at construction."""
    return os.environ.get("SRML_LOCKCHECK", "0") not in ("", "0", "false", "off")


def long_hold_threshold_s() -> float:
    """Long-hold watermark threshold. config["lockcheck_long_hold_ms"] when
    core is already imported (a sys.modules probe — the sanitizer must never
    pay core's import chain from a lock construction), else the env var,
    else 500 ms."""
    import sys

    core = sys.modules.get("spark_rapids_ml_tpu.core")
    if core is not None:
        try:
            return float(core.config.get("lockcheck_long_hold_ms", _DEFAULT_LONG_HOLD_MS)) / 1e3
        except Exception:  # pragma: no cover - teardown ordering
            pass
    try:
        return float(os.environ.get("SRML_LOCKCHECK_LONG_HOLD_MS", _DEFAULT_LONG_HOLD_MS)) / 1e3
    except ValueError:
        return _DEFAULT_LONG_HOLD_MS / 1e3


# ---------------------------------------------------------------- state -----

# the meta lock is a RAW threading.Lock and a strict LEAF: it is only ever
# taken inside the sanitizer with no way to acquire a user lock under it, so
# it can never participate in the orders it polices
_META = threading.Lock()
_EDGES: Dict[Tuple[str, str], Dict[str, Any]] = {}  # guarded-by: _META
_VIOLATIONS: List[Dict[str, Any]] = []  # guarded-by: _META
_MAX_HOLD_S: Dict[str, float] = {}  # guarded-by: _META
_LOCK_NAMES: List[str] = []  # guarded-by: _META

_TLS = threading.local()  # .held: List[dict], .suppress: int


def _held_stack() -> List[Dict[str, Any]]:
    held = getattr(_TLS, "held", None)
    if held is None:
        held = _TLS.held = []
    return held


def _short_stack(skip: int = 3, limit: int = 6) -> List[str]:
    frames = traceback.extract_stack()[:-skip]
    return [f"{os.path.basename(f.filename)}:{f.lineno}:{f.name}" for f in frames[-limit:]]


def _record_violation(v: Dict[str, Any]) -> None:
    """Append + mirror into the flight recorder / telemetry. The suppress
    flag stops the mirror's own lock acquisitions (FlightRecorder._lock and
    the registry lock are themselves CheckedLocks) from re-entering the
    analysis — bounded recursion by construction. The recording cost (first
    call pays lazy imports) is credited back to every held entry's clock so
    the sanitizer never self-inflicts a long-hold violation."""
    _TLS.suppress = getattr(_TLS, "suppress", 0) + 1
    t_start = time.monotonic()
    try:
        with _META:
            _VIOLATIONS.append(v)
        from .. import diagnostics, telemetry

        diagnostics.record_event(
            f"lockcheck.{v['kind']}",
            lock=v.get("lock"),
            held=v.get("held"),
            thread=v.get("thread"),
            first_site=v.get("first_site"),
            hold_s=v.get("hold_s"),
        )
        if telemetry.enabled():
            if v["kind"] == "inversion":
                telemetry.registry().inc("lockcheck.inversions")
            else:
                telemetry.registry().inc("lockcheck.long_holds")
    except Exception:  # pragma: no cover - teardown ordering
        pass
    finally:
        cost = time.monotonic() - t_start
        for h in _held_stack():
            h["t0"] += cost
        _TLS.suppress -= 1


def _on_acquired(name: str) -> None:
    held = _held_stack()
    reentrant = any(h["name"] == name for h in held)
    suppressed = getattr(_TLS, "suppress", 0) > 0
    if not reentrant and not suppressed and held:
        # scan EVERY held lock — one inversion must not stop the forward
        # edges (or further inversions) of the other held entries from
        # being recorded, or a later real ABBA pair against them would be
        # reported clean
        inversions: List[Dict[str, Any]] = []
        with _META:
            for h in held:
                if h["reentrant"]:
                    continue
                fwd = (h["name"], name)
                rev = (name, h["name"])
                if rev in _EDGES and fwd not in _EDGES:
                    inversions.append({"held": h["name"], "first": dict(_EDGES[rev])})
                elif fwd not in _EDGES:
                    _EDGES[fwd] = {
                        "thread": threading.current_thread().name,
                        "stack": _short_stack(),
                    }
        for inv in inversions:
            _record_violation(
                {
                    "kind": "inversion",
                    "lock": name,
                    "held": inv["held"],
                    "thread": threading.current_thread().name,
                    "stack": _short_stack(),
                    "first_site": inv["first"].get("stack"),
                    "t": time.time(),
                }
            )
    # t0 stamped AFTER any violation recording above, so the recording cost
    # (first call pays lazy imports) never counts as hold time
    held.append({"name": name, "t0": time.monotonic(), "reentrant": reentrant})


def _on_released(name: str) -> None:
    held = _held_stack()
    for i in range(len(held) - 1, -1, -1):
        if held[i]["name"] == name:
            entry = held.pop(i)
            break
    else:
        return  # release without a tracked acquire (restore edge cases)
    if entry["reentrant"] or getattr(_TLS, "suppress", 0) > 0:
        return
    dt = time.monotonic() - entry["t0"]
    threshold = long_hold_threshold_s()
    over = dt > threshold
    with _META:
        if dt > _MAX_HOLD_S.get(name, 0.0):
            _MAX_HOLD_S[name] = dt
    if over:
        _record_violation(
            {
                "kind": "long_hold",
                "lock": name,
                "hold_s": dt,
                "threshold_s": threshold,
                "thread": threading.current_thread().name,
                "stack": _short_stack(),
                "t": time.time(),
            }
        )


# ---------------------------------------------------------------- wrapper ---


class CheckedLock:
    """Instrumented Lock/RLock with the `threading` lock interface plus the
    RLock internals (`_is_owned`/`_acquire_restore`/`_release_save`) so
    `threading.Condition` can own one. `cond.wait()` releases through
    `_release_save`, which POPS the held entry — wait time is not hold
    time."""

    def __init__(self, name: str, kind: str = "lock"):
        self.name = name
        self.kind = "rlock" if kind == "condition" else kind
        self._inner = threading.RLock() if self.kind == "rlock" else threading.Lock()
        with _META:
            _LOCK_NAMES.append(name)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            _on_acquired(self.name)
        return ok

    def release(self) -> None:
        _on_released(self.name)
        self._inner.release()

    def __enter__(self) -> "CheckedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def locked(self) -> bool:
        inner_locked = getattr(self._inner, "locked", None)
        if inner_locked is not None:
            return bool(inner_locked())
        return bool(self._inner._is_owned())  # RLock before 3.12

    # -- threading.Condition integration (RLock protocol) ------------------
    def _is_owned(self) -> bool:
        return self._inner._is_owned()

    def _release_save(self):
        _on_released(self.name)
        return self._inner._release_save()

    def _acquire_restore(self, state) -> None:
        self._inner._acquire_restore(state)
        _on_acquired(self.name)

    def __repr__(self) -> str:
        return f"<CheckedLock {self.name} ({self.kind})>"


def make_lock(name: str, kind: str = "lock"):
    """THE framework lock factory: a plain `threading.Lock`/`RLock` while the
    sanitizer is off (zero-cost contract), a `CheckedLock` under
    ``SRML_LOCKCHECK=1``. `name` must be the lock's static-analysis id
    (`<module>.<Class>.<attr>`), so both passes speak one vocabulary."""
    if not enabled():
        return threading.RLock() if kind == "rlock" else threading.Lock()
    return CheckedLock(name, kind)


def make_condition(name: str):
    """`threading.Condition` over a checked RLock when the sanitizer is on,
    a plain Condition otherwise."""
    if not enabled():
        return threading.Condition()
    return threading.Condition(CheckedLock(name, "rlock"))


# ---------------------------------------------------------------- reports ---


def violations() -> List[Dict[str, Any]]:
    with _META:
        return [dict(v) for v in _VIOLATIONS]


def edges() -> Dict[Tuple[str, str], Dict[str, Any]]:
    with _META:
        return {k: dict(v) for k, v in _EDGES.items()}


def report() -> Dict[str, Any]:
    """The violation report ci/test.sh archives: observed order graph,
    inversion/long-hold violations, and per-lock hold watermarks."""
    with _META:
        return {
            "enabled": enabled(),
            "locks": sorted(set(_LOCK_NAMES)),
            "edges": sorted(f"{a} -> {b}" for a, b in _EDGES),
            "inversions": [dict(v) for v in _VIOLATIONS if v["kind"] == "inversion"],
            "long_holds": [dict(v) for v in _VIOLATIONS if v["kind"] == "long_hold"],
            "max_hold_s": dict(sorted(_MAX_HOLD_S.items())),
            "long_hold_threshold_s": long_hold_threshold_s(),
        }


def write_report(path: str) -> Optional[str]:
    rep = report()
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(rep, f, indent=2, default=str)
            f.write("\n")
        os.replace(tmp, path)
    except OSError:  # pragma: no cover - report is best-effort
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    return path


def reset() -> None:
    """Forget the observed graph and violations (test isolation)."""
    with _META:
        _EDGES.clear()
        _VIOLATIONS.clear()
        _MAX_HOLD_S.clear()
        del _LOCK_NAMES[:]


def snapshot() -> Dict[str, Any]:
    """Copy of the global sanitizer state. The lockcheck test fixture
    snapshots before it resets and restores after, so its DELIBERATE
    inversions never poison the CI report while the real lanes'
    observations survive the fixture (a bare reset would erase them —
    the zero-inversion gate would be checking an empty report)."""
    with _META:
        return {
            "edges": {k: dict(v) for k, v in _EDGES.items()},
            "violations": [dict(v) for v in _VIOLATIONS],
            "max_hold_s": dict(_MAX_HOLD_S),
            "lock_names": list(_LOCK_NAMES),
        }


def restore(state: Dict[str, Any]) -> None:
    """Replace the global state with a `snapshot()` — everything observed
    since the snapshot (the fixture test's own deliberate inversions) is
    DISCARDED, everything from before it comes back."""
    with _META:
        _EDGES.clear()
        _EDGES.update({k: dict(v) for k, v in state["edges"].items()})
        _VIOLATIONS[:] = [dict(v) for v in state["violations"]]
        _MAX_HOLD_S.clear()
        _MAX_HOLD_S.update(state["max_hold_s"])
        _LOCK_NAMES[:] = list(state["lock_names"])


def _atexit_report() -> None:  # pragma: no cover - exercised by ci/test.sh
    path = os.environ.get("SRML_LOCKCHECK_REPORT")
    if path and enabled():
        write_report(path)


atexit.register(_atexit_report)
