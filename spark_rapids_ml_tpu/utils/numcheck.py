#
# Runtime numerics sanitizer: the dynamic twin of the static `precision-flow`
# / `prng-discipline` analysis (ci/analysis/rules/numerics.py). The static
# pass PROPOSES that no silent narrowing, low-precision dot, or key misuse
# exists in source; this module VALIDATES the numeric contracts under real
# execution at test time (docs/robustness.md "Numerics contract") — exactly
# the lockcheck pattern (utils/lockcheck.py).
#
# Opt-in via ``SRML_NUMCHECK=1``. Call sites resolve the hook ONCE per
# fit/loop entry (`_nc = numcheck.hook()`); disabled, `hook()` returns None
# and the boundary guard is a single `is not None` test on a local — zero
# wrapper, zero per-iteration work, pinned by tests/test_numcheck.py.
#
# Enabled, the hook runs at the solver boundaries that ALREADY host-fetch —
# the k-means cadence fetch, `run_segmented_while` segment boundaries, the
# streaming solvers' chunk/iteration partials, and the serving plane's
# response assembly — so a check adds arithmetic on bytes the host holds
# anyway, never a new device sync:
#
#   * every float value passed is swept with `np.isfinite`; a NaN/Inf TRIPS:
#     the violation is recorded here, mirrored as a `numcheck.trip`
#     flight-recorder event + `numcheck.trips` counter, and raised as a
#     typed `NumericsError` carrying solver/iteration/stage/value-name;
#   * every checked value's dtype lands in a per-stage dtype WATERMARK
#     (which precisions each boundary actually saw) — the runtime face of
#     the static dtype lattice, and the artifact that catches a silent
#     narrowing the analyzer's local inference could not see;
#   * `numcheck.checks` counts boundary sweeps (the CI gate's evidence that
#     the instrumented lanes actually exercised the hook).
#
# ``SRML_NUMCHECK_REPORT=<path>`` writes the report at interpreter exit —
# the artifact ci/test.sh archives next to the analysis verdict, gated on
# ZERO trips. `snapshot()`/`restore()` give the test fixture the same
# isolation discipline as lockcheck: deliberate test trips never poison the
# CI gate while the real lanes' observations survive.
#
from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

__all__ = [
    "enabled",
    "hook",
    "check",
    "checks",
    "trips",
    "watermarks",
    "report",
    "write_report",
    "reset",
    "snapshot",
    "restore",
]

# a strict LEAF lock (lockcheck discipline): only ever taken inside the
# sanitizer around plain dict/list mutation, never around user code
_META = threading.Lock()
_CHECKS = [0]  # guarded-by: _META
_TRIPS: List[Dict[str, Any]] = []  # guarded-by: _META
_WATERMARKS: Dict[str, Dict[str, int]] = {}  # guarded-by: _META


def enabled() -> bool:
    """Sanitizer opt-in, read per call so tests can flip it; call sites
    resolve it once per fit/loop entry through `hook()`."""
    return os.environ.get("SRML_NUMCHECK", "0") not in ("", "0", "false", "off")


def hook() -> Optional[Callable[..., None]]:
    """THE boundary entry point: the `check` callable when the sanitizer is
    on, None otherwise. Call sites hold the result in a local — the disabled
    path is one env read per fit plus one `is not None` test per boundary
    (zero-cost contract, pinned)."""
    return check if enabled() else None


def check(
    stage: str,
    *,
    solver: str = "",
    iteration: Optional[int] = None,
    watermark: Any = None,
    allow_inf: bool = False,
    **values: Any,
) -> None:
    """Sweep already-host-fetched `values` for NaN/Inf and record dtype
    watermarks for `stage`. `watermark` adds a dtype observation WITHOUT a
    finite-ness sweep — for device arrays whose dtype is free to read but
    whose bytes were not fetched (e.g. the k-means centers between cadence
    checkpoints). `allow_inf=True` restricts the sweep to NaN, for
    boundaries where ±Inf is a DOCUMENTED sentinel (GLM/CD solver state
    carries `jnp.inf` best-loss initializers; top-k pads short result rows
    with `inf` distances) — NaN is a bug everywhere. A non-finite value
    raises `NumericsError` AFTER recording, so the report names the trip
    even when the caller converts the error."""
    marks: List[str] = []
    if watermark is not None:
        marks.append(str(np.dtype(watermark)))
    trip: Optional[Dict[str, Any]] = None
    for name, value in values.items():
        arr = np.asarray(value)
        marks.append(str(arr.dtype))
        if arr.dtype.kind not in "fc":
            continue
        bad_mask = np.isnan(arr) if allow_inf else ~np.isfinite(arr)
        if bool(bad_mask.any()):
            bad = arr[bad_mask]
            n_nan = int(np.isnan(bad).sum())
            n_inf = int(bad.size - n_nan)
            trip = {
                "stage": stage,
                "solver": solver,
                "iteration": iteration,
                "value": name,
                "nan": n_nan,
                "inf": n_inf,
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
                "t": time.time(),
            }
            break
    with _META:
        _CHECKS[0] += 1
        wm = _WATERMARKS.setdefault(stage, {})
        for m in marks:
            wm[m] = wm.get(m, 0) + 1
        if trip is not None:
            _TRIPS.append(dict(trip))
    if trip is None:
        return
    # mirror AFTER the bookkeeping: diagnostics/telemetry failures must not
    # lose the recorded trip, and the typed raise comes last
    try:
        from .. import diagnostics, telemetry

        diagnostics.record_event(
            "numcheck.trip",
            stage=stage,
            solver=solver,
            iteration=iteration,
            value=trip["value"],
            nan=trip["nan"],
            inf=trip["inf"],
        )
        if telemetry.enabled():
            telemetry.registry().inc("numcheck.trips")
    except Exception:  # pragma: no cover - teardown ordering
        pass
    from ..errors import NumericsError

    raise NumericsError(
        stage,
        solver=solver,
        iteration=iteration,
        value_name=trip["value"],
        detail=f"{trip['nan']} NaN / {trip['inf']} Inf over shape "
        f"{tuple(trip['shape'])} {trip['dtype']}",
    )


# ---------------------------------------------------------------- reports ---


def checks() -> int:
    with _META:
        return _CHECKS[0]


def trips() -> List[Dict[str, Any]]:
    with _META:
        return [dict(t) for t in _TRIPS]


def watermarks() -> Dict[str, Dict[str, int]]:
    with _META:
        return {k: dict(v) for k, v in _WATERMARKS.items()}


def report() -> Dict[str, Any]:
    """The report ci/test.sh archives and gates on zero trips: boundary
    sweep count, every trip, and the per-stage dtype watermarks."""
    with _META:
        return {
            "enabled": enabled(),
            "checks": _CHECKS[0],
            "trips": [dict(t) for t in _TRIPS],
            "watermarks": {
                k: dict(sorted(v.items())) for k, v in sorted(_WATERMARKS.items())
            },
        }


def write_report(path: str) -> Optional[str]:
    rep = report()
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(rep, f, indent=2, default=str)
            f.write("\n")
        os.replace(tmp, path)
    except OSError:  # pragma: no cover - report is best-effort
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None
    return path


def reset() -> None:
    """Forget checks, trips, and watermarks (test isolation)."""
    with _META:
        _CHECKS[0] = 0
        del _TRIPS[:]
        _WATERMARKS.clear()


def snapshot() -> Dict[str, Any]:
    """Copy of the global sanitizer state. The numcheck test fixture
    snapshots before it resets and restores after, so its DELIBERATE trips
    never poison the CI gate while the real lanes' observations survive the
    fixture (lockcheck's isolation contract)."""
    with _META:
        return {
            "checks": _CHECKS[0],
            "trips": [dict(t) for t in _TRIPS],
            "watermarks": {k: dict(v) for k, v in _WATERMARKS.items()},
        }


def restore(state: Dict[str, Any]) -> None:
    """Replace the global state with a `snapshot()` — everything observed
    since the snapshot (the fixture test's own deliberate trips) is
    DISCARDED, everything from before it comes back."""
    with _META:
        _CHECKS[0] = int(state["checks"])
        _TRIPS[:] = [dict(t) for t in state["trips"]]
        _WATERMARKS.clear()
        _WATERMARKS.update({k: dict(v) for k, v in state["watermarks"].items()})


def _atexit_report() -> None:  # pragma: no cover - exercised by ci/test.sh
    path = os.environ.get("SRML_NUMCHECK_REPORT")
    if path and enabled():
        write_report(path)


atexit.register(_atexit_report)
