#
# Shared utilities (reference utils.py analog, minus the JVM/py4j pieces which
# have no meaning in the TPU build).
#
from __future__ import annotations

import logging
import os
import sys
from typing import Any, Callable, Dict, Iterable, List, Optional

import numpy as np

_LOGGERS: Dict[str, logging.Logger] = {}


def get_logger(cls_or_name, level: Optional[str] = None) -> logging.Logger:
    """Per-class stderr logger (reference utils.py:281-302).

    The level is resolved ONCE, at logger creation: an explicit `level`
    argument wins, else the `SRML_LOG_LEVEL` env var, else INFO. Cached
    loggers are returned as-is (no per-call level re-derivation), and the
    handler guard makes repeated calls — even across a cleared cache —
    attach at most one stream handler per logger."""
    name = cls_or_name if isinstance(cls_or_name, str) else cls_or_name.__name__
    name = f"spark_rapids_ml_tpu.{name}"
    if name in _LOGGERS:
        return _LOGGERS[name]
    logger = logging.getLogger(name)
    # tolerate lowercase / invalid values ("SRML_LOG_LEVEL=debug" is the
    # common way users type it): normalize, fall back to INFO rather than
    # letting setLevel's ValueError crash every fit
    resolved = (level or os.environ.get("SRML_LOG_LEVEL") or "INFO").upper()
    if not isinstance(logging.getLevelName(resolved), int):
        resolved = "INFO"
    logger.setLevel(resolved)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter("%(asctime)s %(levelname)s %(name)s: %(message)s"))
        logger.addHandler(handler)
        logger.propagate = False
    _LOGGERS[name] = logger
    return logger


def unit_rows(x: np.ndarray, eps: float = 1e-12) -> np.ndarray:
    """Row-normalize to unit L2 norm with a zero-norm guard — THE cosine
    convention shared by every cosine path (ANN index/query/refine, UMAP
    fit/transform): zero rows stay zero. Against unit index vectors a zero
    row's squared euclidean distance is 1, so the kernels' d²/2 conversion
    reports cosine distance 0.5 to EVERYTHING — equidistant, hence
    ranking-neutral, but NOT sklearn's 1.0 convention for zero vectors
    (sklearn defines cos(0, v) = 0). Documented deviation, pinned by
    tests/test_ingest.py::test_unit_rows_zero_row_convention."""
    x = np.asarray(x, np.float32)
    return x / np.maximum(np.linalg.norm(x, axis=1, keepdims=True), eps)


def concat_and_free(chunks: List[np.ndarray]) -> np.ndarray:
    """Memory-frugal concat: frees source chunks as it copies
    (reference utils.py:213-252 `_concat_and_free`)."""
    if len(chunks) == 1:
        return chunks[0]
    total = sum(c.shape[0] for c in chunks)
    first = chunks[0]
    out = np.empty((total,) + first.shape[1:], dtype=first.dtype)
    off = 0
    while chunks:
        c = chunks.pop(0)
        out[off : off + c.shape[0]] = c
        off += c.shape[0]
        del c
    return out


def dtype_to_pytype(dtype) -> type:
    """numpy dtype -> python scalar type for schema-ish introspection
    (reference utils.py:265-277)."""
    kind = np.dtype(dtype).kind
    if kind == "f":
        return float
    if kind in "iu":
        return int
    if kind == "b":
        return bool
    return object


def get_default_params_from_func(func: Callable, unsupported: Iterable[str] = ()) -> Dict[str, Any]:
    """Introspect keyword defaults of a solver entry point, dropping unsupported
    names (reference utils.py:46-71 `_get_default_params_from_func`)."""
    import inspect

    sig = inspect.signature(func)
    out = {}
    for name, p in sig.parameters.items():
        if name in ("self", "X", "y", "sample_weight") or name in unsupported:
            continue
        if p.default is not inspect.Parameter.empty:
            out[name] = p.default
    return out
