#
# Multiclass metrics from confusion-matrix sufficient statistics — a pure-Python
# replication of Spark's Scala MulticlassMetrics (reference
# metrics/MulticlassMetrics.py), so CrossValidator scores come out identical to
# Spark's evaluators without a JVM.
#
# Sufficient stats per partition: {(label, prediction): weighted count} plus an
# optional log-loss partial sum; partitions merge by dict addition.
#
from __future__ import annotations

from typing import Dict, Optional, Tuple

__all__ = ["MulticlassMetrics"]


class MulticlassMetrics:
    SUPPORTED_MULTI_CLASS_METRIC_NAMES = [
        "f1",
        "accuracy",
        "weightedPrecision",
        "weightedRecall",
        "weightedTruePositiveRate",
        "weightedFalsePositiveRate",
        "weightedFMeasure",
        "truePositiveRateByLabel",
        "falsePositiveRateByLabel",
        "precisionByLabel",
        "recallByLabel",
        "fMeasureByLabel",
        "logLoss",
        "hammingLoss",
    ]

    def __init__(
        self,
        tp: Optional[Dict[float, float]] = None,
        fp: Optional[Dict[float, float]] = None,
        label: Optional[Dict[float, float]] = None,
        label_count: float = 0.0,
        log_loss: Optional[float] = None,
    ):
        self._tp_by_class = tp or {}
        self._fp_by_class = fp or {}
        self._label_count_by_class = label or {}
        self._label_count = label_count
        self._log_loss = log_loss

    # -- construction from sufficient statistics ---------------------------
    @classmethod
    def from_confusion(
        cls, confusion: Dict[Tuple[float, float], float], log_loss: Optional[float] = None
    ) -> "MulticlassMetrics":
        """confusion: {(label, prediction): weighted count}."""
        tp: Dict[float, float] = {}
        fp: Dict[float, float] = {}
        label_count: Dict[float, float] = {}
        total = 0.0
        for (lbl, pred_), cnt in confusion.items():
            total += cnt
            label_count[lbl] = label_count.get(lbl, 0.0) + cnt
            tp.setdefault(lbl, 0.0)
            fp.setdefault(pred_, 0.0)
            if lbl == pred_:
                tp[lbl] = tp.get(lbl, 0.0) + cnt
            else:
                fp[pred_] = fp.get(pred_, 0.0) + cnt
        return cls(tp, fp, label_count, total, log_loss)

    @staticmethod
    def merge_confusion(
        a: Dict[Tuple[float, float], float], b: Dict[Tuple[float, float], float]
    ) -> Dict[Tuple[float, float], float]:
        out = dict(a)
        for k, v in b.items():
            out[k] = out.get(k, 0.0) + v
        return out

    # -- per-label metrics (reference MulticlassMetrics.py:40-121) ----------
    def _precision(self, label: float) -> float:
        tp = self._tp_by_class.get(label, 0.0)
        fp = self._fp_by_class.get(label, 0.0)
        return 0.0 if (tp + fp) == 0 else tp / (tp + fp)

    def _recall(self, label: float) -> float:
        cnt = self._label_count_by_class.get(label, 0.0)
        return 0.0 if cnt == 0 else self._tp_by_class.get(label, 0.0) / cnt

    def _f_measure(self, label: float, beta: float = 1.0) -> float:
        p = self._precision(label)
        r = self._recall(label)
        b2 = beta * beta
        return 0.0 if (p + r) == 0 else (1 + b2) * p * r / (b2 * p + r)

    def false_positive_rate(self, label: float) -> float:
        fp = self._fp_by_class.get(label, 0.0)
        denom = self._label_count - self._label_count_by_class.get(label, 0.0)
        return 0.0 if denom == 0 else fp / denom

    def weighted_fmeasure(self, beta: float = 1.0) -> float:
        return sum(
            self._f_measure(k, beta) * v / self._label_count
            for k, v in self._label_count_by_class.items()
        )

    def accuracy(self) -> float:
        return sum(self._tp_by_class.values()) / self._label_count

    def weighted_precision(self) -> float:
        return sum(
            self._precision(k) * v / self._label_count
            for k, v in self._label_count_by_class.items()
        )

    def weighted_recall(self) -> float:
        return sum(
            self._recall(k) * v / self._label_count for k, v in self._label_count_by_class.items()
        )

    def weighted_true_positive_rate(self) -> float:
        return self.weighted_recall()

    def weighted_false_positive_rate(self) -> float:
        return sum(
            self.false_positive_rate(k) * v / self._label_count
            for k, v in self._label_count_by_class.items()
        )

    def hamming_loss(self) -> float:
        return 1.0 - self.accuracy()

    def log_loss(self) -> float:
        assert self._log_loss is not None, "log-loss sufficient stats were not collected"
        return self._log_loss / self._label_count

    def evaluate(self, evaluator) -> float:
        """Dispatch on the evaluator's metricName (reference MulticlassMetrics.py:149-180)."""
        metric = evaluator.getMetricName()
        if metric == "f1":
            return self.weighted_fmeasure()
        if metric == "accuracy":
            return self.accuracy()
        if metric == "weightedPrecision":
            return self.weighted_precision()
        if metric == "weightedRecall":
            return self.weighted_recall()
        if metric == "weightedTruePositiveRate":
            return self.weighted_true_positive_rate()
        if metric == "weightedFalsePositiveRate":
            return self.weighted_false_positive_rate()
        if metric == "weightedFMeasure":
            return self.weighted_fmeasure(evaluator.getBeta())
        if metric == "truePositiveRateByLabel":
            return self._recall(evaluator.getMetricLabel())
        if metric == "falsePositiveRateByLabel":
            return self.false_positive_rate(evaluator.getMetricLabel())
        if metric == "precisionByLabel":
            return self._precision(evaluator.getMetricLabel())
        if metric == "recallByLabel":
            return self._recall(evaluator.getMetricLabel())
        if metric == "fMeasureByLabel":
            return self._f_measure(evaluator.getMetricLabel(), evaluator.getBeta())
        if metric == "hammingLoss":
            return self.hamming_loss()
        if metric == "logLoss":
            return self.log_loss()
        raise ValueError(f"Unsupported metric name {metric!r}")
