#
# Regression metrics from streaming moment buffers — replicates Spark's
# SummarizerBuffer + RegressionMetrics (reference metrics/RegressionMetrics.py),
# so CV scores all models of a fold from one pass of per-model sufficient stats.
#
# Each buffer tracks weighted moments of the 2-column stream
# [label, label - prediction]: currMean, currM2n (Σw(x-μ)²), currM2 (Σw x²),
# currL1 (Σw|x|), totalCnt, weightSum — with the numerically-stable streaming
# merge (reference RegressionMetrics.py:63-168).
#
from __future__ import annotations

from typing import List, Sequence

import numpy as np

__all__ = ["RegressionMetrics", "_SummarizerBuffer"]


class _SummarizerBuffer:
    def __init__(
        self,
        mean: Sequence[float] = (0.0, 0.0),
        m2n: Sequence[float] = (0.0, 0.0),
        m2: Sequence[float] = (0.0, 0.0),
        l1: Sequence[float] = (0.0, 0.0),
        total_cnt: int = 0,
        weight_sum: float = 0.0,
        weight_square_sum: float = 0.0,
    ):
        self._curr_mean = np.asarray(mean, dtype=np.float64).copy()
        self._curr_m2n = np.asarray(m2n, dtype=np.float64).copy()
        self._curr_m2 = np.asarray(m2, dtype=np.float64).copy()
        self._curr_l1 = np.asarray(l1, dtype=np.float64).copy()
        self._total_cnt = int(total_cnt)
        self._weight_sum = float(weight_sum)
        self._weight_square_sum = float(weight_square_sum)
        self._num_cols = len(self._curr_mean)

    @classmethod
    def from_values(cls, label: np.ndarray, prediction: np.ndarray, weight: np.ndarray) -> "_SummarizerBuffer":
        """Build the buffer for one partition from raw columns."""
        label = np.asarray(label, dtype=np.float64)
        residual = label - np.asarray(prediction, dtype=np.float64)
        w = np.asarray(weight, dtype=np.float64)
        cols = np.stack([label, residual], axis=1)  # [n, 2]
        weight_sum = float(w.sum())
        mean = (w[:, None] * cols).sum(axis=0) / weight_sum
        m2n = (w[:, None] * (cols - mean) ** 2).sum(axis=0)
        m2 = (w[:, None] * cols**2).sum(axis=0)
        l1 = (w[:, None] * np.abs(cols)).sum(axis=0)
        return cls(mean, m2n, m2, l1, len(label), weight_sum, float((w**2).sum()))

    def merge(self, other: "_SummarizerBuffer") -> "_SummarizerBuffer":
        """Streaming merge of two buffers (reference RegressionMetrics.py:63-100)."""
        if other._weight_sum == 0:
            return self
        if self._weight_sum == 0:
            return other
        total_w = self._weight_sum + other._weight_sum
        delta = other._curr_mean - self._curr_mean
        mean = self._curr_mean + delta * (other._weight_sum / total_w)
        m2n = (
            self._curr_m2n
            + other._curr_m2n
            + delta * delta * self._weight_sum * other._weight_sum / total_w
        )
        return _SummarizerBuffer(
            mean,
            m2n,
            self._curr_m2 + other._curr_m2,
            self._curr_l1 + other._curr_l1,
            self._total_cnt + other._total_cnt,
            total_w,
            self._weight_square_sum + other._weight_square_sum,
        )

    @property
    def total_count(self) -> int:
        return self._total_cnt

    @property
    def weight_sum(self) -> float:
        return self._weight_sum

    def mean(self, col: int) -> float:
        return float(self._curr_mean[col])

    def m2n(self, col: int) -> float:
        return float(self._curr_m2n[col])

    def m2(self, col: int) -> float:
        return float(self._curr_m2[col])

    def l1(self, col: int) -> float:
        return float(self._curr_l1[col])


_LABEL, _RESIDUAL = 0, 1


class RegressionMetrics:
    """rmse/mse/r2/mae/explainedVariance from a (merged) SummarizerBuffer
    (reference RegressionMetrics.py:170-267)."""

    def __init__(self, buffer: _SummarizerBuffer):
        self._buffer = buffer

    @classmethod
    def from_values(cls, label, prediction, weight=None) -> "RegressionMetrics":
        label = np.asarray(label)
        if weight is None:
            weight = np.ones_like(label, dtype=np.float64)
        return cls(_SummarizerBuffer.from_values(label, prediction, weight))

    @classmethod
    def merge_all(cls, metrics: List["RegressionMetrics"]) -> "RegressionMetrics":
        buf = metrics[0]._buffer
        for m in metrics[1:]:
            buf = buf.merge(m._buffer)
        return cls(buf)

    @property
    def _ss_err(self) -> float:  # Σw·residual²
        return self._buffer.m2(_RESIDUAL)

    @property
    def _ss_tot(self) -> float:  # Σw(y-ȳ)²
        return self._buffer.m2n(_LABEL)

    def mean_squared_error(self) -> float:
        return self._ss_err / self._buffer.weight_sum

    def root_mean_squared_error(self) -> float:
        return float(np.sqrt(self.mean_squared_error()))

    def mean_absolute_error(self) -> float:
        return self._buffer.l1(_RESIDUAL) / self._buffer.weight_sum

    def r2(self, through_origin: bool = False) -> float:
        # through-origin r2 normalizes by Σw·y² instead of Σw(y-ȳ)² (Spark parity)
        denom = self._buffer.m2(_LABEL) if through_origin else self._ss_tot
        return 1.0 - self._ss_err / denom

    def explained_variance(self) -> float:
        # Var(y) - Var(residual) form (Spark's explainedVariance)
        return (self._ss_tot - self._buffer.m2n(_RESIDUAL)) / self._buffer.weight_sum

    def evaluate(self, evaluator) -> float:
        metric = evaluator.getMetricName()
        if metric == "rmse":
            return self.root_mean_squared_error()
        if metric == "mse":
            return self.mean_squared_error()
        if metric == "mae":
            return self.mean_absolute_error()
        if metric == "r2":
            through_origin = bool(
                evaluator.hasParam("throughOrigin") and evaluator.getOrDefault("throughOrigin")
            ) if hasattr(evaluator, "hasParam") else False
            return self.r2(through_origin)
        if metric == "var":
            return self.explained_variance()
        raise ValueError(f"Unsupported metric name {metric!r}")
