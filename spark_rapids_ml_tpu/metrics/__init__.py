#
# Metrics subsystem — driver-side metric aggregation from per-partition
# sufficient statistics, replicating Spark's Scala MulticlassMetrics /
# RegressionMetrics / SummarizerBuffer so CrossValidator can score all models
# from ONE transform pass (reference metrics/__init__.py, MulticlassMetrics.py,
# RegressionMetrics.py; SURVEY.md §2.1).
#
from __future__ import annotations

from collections import namedtuple

# Which sufficient-stats schema a fused transform+evaluate pass must produce
# (reference metrics/__init__.py:22-37).
transform_evaluate_metric = namedtuple(
    "transform_evaluate_metric", ("accuracy_like", "log_loss", "regression")
)("accuracy_like", "log_loss", "regression")


class EvalMetricInfo:
    """What the evaluator needs from the transform pass
    (reference metrics/__init__.py:31-40)."""

    def __init__(self, eval_metric: str, eps: float = 1e-15):
        self.eval_metric = eval_metric
        self.eps = eps


from .MulticlassMetrics import MulticlassMetrics  # noqa: E402,F401
from .RegressionMetrics import RegressionMetrics, _SummarizerBuffer  # noqa: E402,F401
