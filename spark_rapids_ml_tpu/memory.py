#
# HBM admission control: the memory-safety plane (docs/robustness.md
# "Memory safety").
#
# The reference inherits cuML MG's full-device-residency assumption (PAPER.md
# L3): a dataset over HBM is an uncatchable XLA RESOURCE_EXHAUSTED crash that
# under SPMD tears down the whole clique. This module makes memory a BUDGETED
# resource instead: every fit entering `core._call_fit_func` gets a preflight
# ADMISSION VERDICT —
#
#   RESIDENT  the placement + solver working set fits the per-device budget:
#             lay the dataset out in HBM as before;
#   STREAM    the resident working set does not fit, but the out-of-core one
#             (double-buffered row chunks + solver workspace) does: the fit
#             demotes to the streaming solvers (ops/streaming.py) and the
#             `fit.demotions` counter advances;
#   raise     even streaming cannot fit — a typed `HbmBudgetError` carrying
#             the estimate, the capacity, and the LARGEST term, so the failure
#             names what doesn't fit instead of surfacing as a raw XLA error.
#
# Estimates are deliberately simple, exact formulas (pinned by
# tests/test_memory.py against analytic byte counts): per-device placement
# bytes for the dense and CSR->ELL (incl. padding) layouts, plus per-solver
# workspace from the estimator hook `_solver_workspace_terms` (GLM logits +
# L-BFGS history, k-means tile buffers AND its predict-side assignment tile
# — `config["distance_tile_rows"]` rows through the shared distance core,
# so an admitted fit cannot OOM at transform — PCA/linear X'X). A fraction of the
# capacity (`config["hbm_headroom_fraction"]`) is reserved as headroom for the
# transform bucket ladder, compiled-program scratch, and allocator
# fragmentation — the budget is capacity * (1 - headroom).
#
# Capacity resolution order: a chaos-injected budget (`oom:budget=` faults,
# parallel/chaos.py) > `config["hbm_budget_bytes"]` > the minimum
# `Device.memory_stats()["bytes_limit"]` over the mesh where the backend
# exposes it (TPU/GPU yes, CPU None). No capacity information means no
# budgeting: the verdict is RESIDENT, exactly the pre-PR behavior.
#
# This module (and telemetry.py's watermark sampler) is the one sanctioned
# `memory_stats()` owner — the ci/analysis gate forbids direct calls elsewhere in the
# framework (`# hbm-ok` waiver).
#
# SHARED LEDGER (docs/scheduling.md "The shared ledger"): both admission
# controllers here — `admit_fit` and `admit_model_load` — charge against the
# budget MINUS what the process-wide `scheduler.HbmLedger` already holds, and
# every admission reserves its estimate there. A fit running next to resident
# serving models (or other co-admitted fits) can no longer jointly overshoot
# HBM: the fit sees the models' reserved bytes and demotes/refuses
# accordingly, and vice versa. The companion ci/analysis rule `ledger-bypass`
# keeps capacity math in this module and `scheduler/` (`# ledger-ok` waiver
# at the two sanctioned call sites).
#
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .errors import HbmBudgetError

RESIDENT = "resident"
STREAM = "stream"

# floor for auto-derived streaming chunk rows: chunks smaller than this spend
# more wall time on dispatch than transfer
MIN_STREAM_CHUNK_ROWS = 256
# auto chunk size when no capacity information bounds it
DEFAULT_STREAM_CHUNK_ROWS = 65536


@dataclass
class MemoryEstimate:
    """A per-device byte estimate as named terms (placement.X, workspace.gram,
    ...) so failures and logs can name the dominant line item."""

    terms: Dict[str, int] = field(default_factory=dict)

    def total(self) -> int:
        return int(sum(self.terms.values()))

    def largest(self) -> Tuple[str, int]:
        if not self.terms:
            return ("", 0)
        name = max(self.terms, key=lambda k: self.terms[k])
        return (name, int(self.terms[name]))


@dataclass
class AdmissionDecision:
    """The verdict `core` applies at fit entry. `estimate` is the per-device
    working set backing the verdict (the RESIDENT one for resident fits, the
    STREAMING one for demoted fits); `chunk_rows` is the admitted streaming
    chunk size (0 on the resident path); `demoted` marks a fit that ASKED for
    residency and was demoted (budget, or an OOM-retry force)."""

    verdict: str
    estimate: MemoryEstimate
    capacity_bytes: Optional[int] = None
    budget_bytes: Optional[int] = None
    chunk_rows: int = 0
    reason: str = ""
    demoted: bool = False
    # devices the admitted working set spans — the chip-seconds multiplier
    # for the ledger's per-tenant accounting (a cache-hit re-reserve must
    # charge the same chips the original admission did)
    chips: int = 1
    # the shared-ledger claim backing this admission (scheduler.HbmReservation),
    # or None when a scheduler job owns the claim (the job's reservation was
    # RESIZED instead — the scheduler releases it at job end). Fit-side claims
    # are released by the fit driver's finally (core._call_fit_func); serving
    # claims by ModelRegistry eviction.
    reservation: Any = None

    def stamp(self) -> Dict[str, Any]:
        """The JSON-able summary `core` stamps onto ``model._fit_metrics``."""
        name, nbytes = self.estimate.largest()
        return {
            "verdict": self.verdict,
            "reason": self.reason,
            "estimate_bytes": self.estimate.total(),
            "capacity_bytes": self.capacity_bytes,
            "budget_bytes": self.budget_bytes,
            "chunk_rows": self.chunk_rows,
            "largest_term": name,
            "largest_term_bytes": nbytes,
        }


def rows_per_device(n_rows: int, n_devices: int) -> int:
    """Padded per-device row count of the mesh layout: rows are padded to a
    multiple of the device count (mesh.shard_row_slices semantics)."""
    n_devices = max(1, int(n_devices))
    n_pad = -(-max(0, int(n_rows)) // n_devices) * n_devices
    return n_pad // n_devices


def ell_k_max(csr: Any) -> int:
    """Widest-row nnz of a scipy CSR — the padded-ELL row width (min 1,
    mirroring ops/sparse.csr_to_ell)."""
    if csr.shape[0] == 0:
        return 1
    return max(1, int(np.diff(csr.indptr).max()))


def placement_terms(
    extracted: Any, dtype: Any, n_devices: int
) -> Dict[str, int]:
    """Per-device HBM bytes of the resident placement of `extracted`.

    Dense: the row-sharded [n_pad, d] block (rows padded to a multiple of the
    device count). Sparse: the CSR->ELL conversion's values [n_pad, k_max] +
    int32 indices [n_pad, k_max] — the padding cells are REAL placed bytes,
    which is exactly why a skewed k_max can blow the budget. The label column
    (when supervised data carries one) and the weight vector ride along as one
    scalar per row each. Pinned against analytic byte counts by
    tests/test_memory.py."""
    itemsize = int(np.dtype(dtype).itemsize)
    rows_dev = rows_per_device(extracted.n_rows, n_devices)
    terms: Dict[str, int] = {}
    if extracted.is_sparse:
        k_max = ell_k_max(extracted.features)
        terms["placement.ell_values"] = rows_dev * k_max * itemsize
        terms["placement.ell_indices"] = rows_dev * k_max * 4  # int32
    else:
        terms["placement.X"] = rows_dev * int(extracted.n_cols) * itemsize
    if extracted.label is not None:
        terms["placement.y"] = rows_dev * itemsize
    terms["placement.w"] = rows_dev * itemsize
    return terms


def row_bytes(extracted: Any, dtype: Any) -> int:
    """Placed bytes of ONE row (features + label + weight) — the streaming
    chunk sizing unit. ELL rows cost k_max * (4 + itemsize)."""
    itemsize = int(np.dtype(dtype).itemsize)
    if extracted.is_sparse:
        per_row = ell_k_max(extracted.features) * (4 + itemsize)
    else:
        per_row = int(extracted.n_cols) * itemsize
    if extracted.label is not None:
        per_row += itemsize
    return per_row + itemsize  # + weight


def workspace_estimate(
    estimator: Any, extracted: Any, n_devices: int, rows_dev: Optional[int] = None
) -> MemoryEstimate:
    """Per-solver workspace terms from the estimator hook
    (`_solver_workspace_terms`), prefixed ``workspace.``.

    `rows_dev` is the per-device row count ROW-SCALING terms are evaluated
    at: the full padded shard for a resident fit (default), the CHUNK shard
    for a streaming one — out-of-core solvers only ever hold one chunk's
    logits / tile buffers on device (accumulators, gram blocks, and L-BFGS
    history are row-count independent and unaffected)."""
    dtype = np.float32 if getattr(estimator, "_float32_inputs", True) else np.float64
    itemsize = int(np.dtype(dtype).itemsize)
    if rows_dev is None:
        rows_dev = rows_per_device(extracted.n_rows, n_devices)
    hook = getattr(estimator, "_solver_workspace_terms", None)
    terms: Dict[str, int] = {}
    if hook is not None:
        raw = hook(rows_dev, int(extracted.n_cols), dict(estimator._solver_params), itemsize)
        for name, nbytes in (raw or {}).items():
            key = name if name.startswith("workspace.") else f"workspace.{name}"
            terms[key] = int(nbytes)
    return MemoryEstimate(terms)


def resident_estimate(
    estimator: Any, extracted: Any, n_devices: int
) -> MemoryEstimate:
    """Full resident working set: placement + solver workspace, per device."""
    dtype = np.float32 if getattr(estimator, "_float32_inputs", True) else np.float64
    est = MemoryEstimate(dict(placement_terms(extracted, dtype, n_devices)))
    est.terms.update(workspace_estimate(estimator, extracted, n_devices).terms)
    return est


def streaming_estimate(
    estimator: Any, extracted: Any, n_devices: int, chunk_rows: int
) -> MemoryEstimate:
    """Streaming working set: TWO chunks resident at once (the double buffer
    — chunk N computing while chunk N+1's transfer is in flight) plus the
    solver workspace with its row-scaling terms (per-row logits, assignment
    tile buffers) evaluated at the CHUNK shard — out-of-core solvers never
    hold more than one chunk's row-proportional state on device."""
    dtype = np.float32 if getattr(estimator, "_float32_inputs", True) else np.float64
    rb = row_bytes(extracted, dtype)
    # per-device: each device holds its shard of BOTH in-flight chunks
    chunk_dev = rows_per_device(chunk_rows, n_devices)
    full_dev = rows_per_device(extracted.n_rows, n_devices)
    est = MemoryEstimate({"stream.chunk_buffers": 2 * chunk_dev * rb})
    est.terms.update(
        workspace_estimate(
            estimator, extracted, n_devices, rows_dev=min(chunk_dev, full_dev)
        ).terms
    )
    return est


def device_capacity_bytes(
    mesh: Any = None, devices: Any = None, *, consume_chaos: bool = True
) -> Optional[int]:
    """Per-device HBM capacity the admission check budgets against.

    Resolution order: chaos-injected budget (`oom:budget=` fault — the
    shrunken-budget injection that makes the whole demotion ladder testable
    without a real TPU) > ``config["hbm_budget_bytes"]`` > the minimum
    ``Device.memory_stats()['bytes_limit']`` over the mesh devices (or the
    explicit `devices` list — the serving plane budgets its one local device
    without standing up a mesh). Returns None when nothing is known (CPU
    backend, no override) — no budgeting. ``consume_chaos=False`` skips the
    injected-budget probe WITHOUT spending a plan firing — the scheduler's
    bin-packing passes read capacity many times per admission, and each
    `oom:budget=` entry must demote exactly `times` FIT admissions."""
    from .core import config
    from .parallel import chaos

    if consume_chaos:
        injected = chaos.injected_hbm_budget()
        if injected is not None:
            return int(injected)
    override = config.get("hbm_budget_bytes")
    if override:
        return int(override)
    if devices is None:
        if mesh is None:
            return None
        devices = list(mesh.devices.flatten())
    limit: Optional[int] = None
    for d in devices:
        try:
            stats = d.memory_stats()  # hbm-ok: memory.py is the budget owner
        except Exception:
            stats = None
        if not stats:
            continue
        cap = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
        if cap:
            limit = int(cap) if limit is None else min(limit, int(cap))
    return limit


def headroom_fraction() -> float:
    from .core import config

    try:
        f = float(config.get("hbm_headroom_fraction", 0.1))
    except (TypeError, ValueError):
        return 0.1
    return min(max(f, 0.0), 0.9)


def _configured_chunk_rows() -> int:
    from .core import config

    try:
        return max(0, int(config.get("stream_chunk_rows", 0)))
    except (TypeError, ValueError):
        return 0


def _claimed_chips(job_res: Any = None) -> Optional[Tuple[int, ...]]:
    """The chip set this admission is scoped to, if any: the enclosing
    scheduler job's PLACED reservation (2-D co-admission), else the ambient
    `parallel.mesh.chip_scope` pin (a sweep shard or test carving a
    sub-mesh by hand). None means the legacy whole-pool contract."""
    if job_res is not None and getattr(job_res, "chip_ids", None) is not None:
        return tuple(job_res.chip_ids)
    from .parallel.mesh import current_chip_scope

    scoped = current_chip_scope()
    if scoped is None:
        return None
    return tuple(int(getattr(d, "id", i)) for i, d in enumerate(scoped))


def admit_fit(
    estimator: Any,
    extracted: Any,
    ctx: Any,
    *,
    force_stream: bool = False,
) -> AdmissionDecision:
    """Issue the admission verdict for one fit (see module docstring).

    Budgets against the capacity MINUS what the shared `scheduler.HbmLedger`
    already holds (resident serving models, co-admitted fits), and reserves
    the admitted estimate there — under the ledger's admission lock, so
    concurrent admissions cannot both claim the same free bytes. Inside a
    scheduler job (`scheduler.context.current_job`) the job's queue-time
    reservation is RESIZED instead of duplicated, and a job demoted after
    repeated preemption is force-streamed.

    Raises `HbmBudgetError` — naming the largest term — when even the
    streaming working set exceeds the remaining budget, when the estimator
    has no out-of-core path, or when the fit runs under multi-process SPMD
    (the streaming pipeline is single-controller; an SPMD over-budget fit
    must fail typed rather than OOM the clique). `force_stream` is the
    OOM-retry entry: skip the resident check and admit the streaming path
    (capacity may be unknown — a real allocation failure is evidence
    enough)."""
    from . import telemetry
    from .ops_plane import audit as _audit
    from .scheduler import context as _sched_ctx
    from .scheduler.ledger import global_ledger

    mesh = ctx.mesh
    n_devices = int(mesh.devices.size)
    capacity = device_capacity_bytes(mesh)
    budget = (
        None if capacity is None else int(capacity * (1.0 - headroom_fraction()))
    )
    if telemetry.enabled() and capacity is not None:
        telemetry.registry().gauge("memory.capacity_bytes", capacity)

    led = global_ledger()
    job = _sched_ctx.current_job()
    sched_demoted = job is not None and getattr(job, "demote_to_stream", False)
    if sched_demoted:
        force_stream = True
    job_res = getattr(job, "reservation", None) if job is not None else None
    my_chips = _claimed_chips(job_res)

    with led.admission():
        if budget is None:
            held = 0
        elif my_chips:
            # 2-D placement: a chip-scoped fit budgets against ITS chips'
            # byte book — bytes held by a co-admitted job on DISJOINT chips
            # must not shrink this fit's budget, while whole-pool claims
            # (chip_ids=None) still count everywhere
            held = max(
                led.reserved_bytes_on(c, exclude=job_res) for c in my_chips
            )
        else:
            held = led.reserved_bytes(exclude=job_res)
        avail = None if budget is None else max(0, budget - held)
        held_note = (
            f" ({held} bytes/device already reserved in the shared ledger "
            "by other fits/serving models"
            + (" on this fit's chip set" if my_chips else "")
            + ")"
            if held
            else ""
        )

        def _grant(est_obj, verdict, chunk_rows=0, reason="", demoted=False):
            """Record the admitted claim in the shared ledger and build the
            decision. Job-owned claims resize; standalone fits reserve."""
            if job_res is not None:
                led.resize(job_res, est_obj.total())
                reservation = None  # the scheduler releases the job's claim
            else:
                reservation = led.reserve(
                    f"fit:{type(estimator).__name__}", "fit", est_obj.total(),
                    chips=n_devices, chip_ids=my_chips,
                )
            led.note_admission(budget)
            # one audit-trail record per admission verdict — the queryable
            # side of the _fit_metrics["admission"] stamp (ops_plane.audit)
            _audit.record_decision(
                "demotion" if demoted else "admission", "fit", verdict,
                subject=type(estimator).__name__, reason=reason,
                estimate_bytes=est_obj.total(), budget_bytes=budget,
                chunk_rows=int(chunk_rows),
            )
            return AdmissionDecision(
                verdict=verdict,
                estimate=est_obj,
                capacity_bytes=capacity,
                budget_bytes=budget,
                chunk_rows=int(chunk_rows),
                reason=reason,
                demoted=demoted,
                chips=n_devices,
                reservation=reservation,
            )

        def _refuse(exc):
            led.note_admission(budget)  # refusals fire the admission hooks too
            _audit.record_decision(
                "admission", "fit", "refused",
                subject=type(estimator).__name__, reason=str(exc),
                estimate_bytes=getattr(exc, "estimate_bytes", None),
                budget_bytes=budget,
            )
            raise exc

        res = resident_estimate(estimator, extracted, n_devices)
        if not force_stream:
            if telemetry.enabled():
                telemetry.registry().gauge("memory.estimate_bytes", res.total())
            if avail is None or res.total() <= avail:
                return _grant(
                    res, RESIDENT,
                    reason="fits" if budget is not None else "no capacity information",
                )
            reason = (
                f"resident working set {res.total()} bytes/device exceeds the "
                f"{budget}-byte budget{held_note}"
            )
        elif sched_demoted:
            reason = (
                "scheduler demotion: preempted "
                f"{getattr(job, 'preemptions', 0)} time(s) "
                "(config['sched_max_preemptions'])"
            )
        else:
            reason = "backend OOM caught; retrying out-of-core"

        # ---- the streaming side of the ladder ----------------------------
        if not getattr(estimator, "_supports_streaming_fit", False):
            name, nbytes = res.largest()
            _refuse(HbmBudgetError(
                f"{type(estimator).__name__} fit does not fit device memory "
                f"and has no out-of-core streaming path{held_note}",
                estimate_bytes=res.total(),
                capacity_bytes=budget,
                largest_term=name,
                largest_term_bytes=nbytes,
                terms=res.terms,
            ))
        if ctx is not None and getattr(ctx, "is_spmd", False):
            name, nbytes = res.largest()
            _refuse(HbmBudgetError(
                f"{type(estimator).__name__} fit does not fit device memory; "
                "the out-of-core streaming path is single-controller only "
                "(multi-process SPMD fits must fit resident)",
                estimate_bytes=res.total(),
                capacity_bytes=budget,
                largest_term=name,
                largest_term_bytes=nbytes,
                terms=res.terms,
            ))

        dtype = np.float32 if getattr(estimator, "_float32_inputs", True) else np.float64
        rb = row_bytes(extracted, dtype)
        chunk_rows = _configured_chunk_rows()
        if chunk_rows <= 0:
            if avail is None:
                chunk_rows = DEFAULT_STREAM_CHUNK_ROWS
            else:
                # size against the floor-chunk workspace (row-scaling
                # workspace terms grow with the chunk; the post-sizing check
                # below shrinks back toward the floor if the chosen chunk's
                # full estimate overshoots)
                floor_dev = rows_per_device(
                    min(MIN_STREAM_CHUNK_ROWS, max(1, int(extracted.n_rows))), n_devices
                )
                ws = workspace_estimate(
                    estimator, extracted, n_devices, rows_dev=floor_dev
                ).total()
                room = avail - ws
                # two in-flight chunks per device; chunk rows are a whole-chunk
                # (all-devices) count, so a device holds chunk_rows/n_devices rows
                chunk_rows = max(
                    MIN_STREAM_CHUNK_ROWS,
                    (room // (2 * rb)) * n_devices if room > 0 else 0,
                )
        chunk_rows = max(1, min(int(chunk_rows), max(1, int(extracted.n_rows))))

        stream = streaming_estimate(estimator, extracted, n_devices, chunk_rows)
        if avail is not None and stream.total() > avail:
            # shrink toward the floor before giving up: the chunk size is the
            # only knob the admission controller owns
            floor = min(MIN_STREAM_CHUNK_ROWS, chunk_rows)
            stream_floor = streaming_estimate(estimator, extracted, n_devices, floor)
            if stream_floor.total() > avail:
                name, nbytes = stream_floor.largest()
                _refuse(HbmBudgetError(
                    f"{type(estimator).__name__} fit does not fit device "
                    "memory even on the out-of-core streaming "
                    f"path{held_note}",
                    estimate_bytes=stream_floor.total(),
                    capacity_bytes=budget,
                    largest_term=name,
                    largest_term_bytes=nbytes,
                    terms=stream_floor.terms,
                ))
            chunk_rows, stream = floor, stream_floor
        if telemetry.enabled():
            telemetry.registry().gauge("memory.estimate_bytes", stream.total())
        return _grant(
            stream, STREAM, chunk_rows=chunk_rows, reason=reason, demoted=True
        )


# ------------------------------------------------------- serving plane ------


def model_serve_estimate(model: Any, bucket_rows_count: int) -> MemoryEstimate:
    """Per-device working set of a RESIDENT serving model: the placement of
    its state arrays (`_serve_placement_terms` — replicated, so per-device =
    full size) plus the per-bucket predict workspace
    (`_serve_workspace_terms` at the ladder cap), exactly the fit-side
    placement + workspace split (module docstring)."""
    dtype = np.float32 if getattr(model, "_float32_inputs", True) else np.float64
    itemsize = int(np.dtype(dtype).itemsize)
    terms: Dict[str, int] = {}
    hook = getattr(model, "_serve_placement_terms", None)
    for name, nbytes in ((hook() if hook is not None else None) or {}).items():
        key = name if name.startswith("placement.") else f"placement.{name}"
        terms[key] = int(nbytes)
    whook = getattr(model, "_serve_workspace_terms", None)
    raw = whook(int(bucket_rows_count), itemsize) if whook is not None else None
    for name, nbytes in (raw or {}).items():
        key = name if name.startswith("workspace.") else f"workspace.{name}"
        terms[key] = int(nbytes)
    return MemoryEstimate(terms)


def admit_model_load(
    model: Any,
    *,
    resident_bytes: int = 0,
    bucket_rows_count: Optional[int] = None,
    devices: Any = None,
    tenant: Optional[str] = None,
    chip_ids: Any = None,
) -> AdmissionDecision:
    """Admission verdict for loading a fitted model into the serving plane
    (docs/serving.md): params get a placement estimate and a per-bucket
    predict workspace term, exactly like fits. `resident_bytes` is what the
    registry's already-resident models hold — the load is admitted against
    the REMAINING budget. There is no streaming demotion for serving (a
    model either resides or the load is refused typed), so the two verdicts
    are RESIDENT or a raised `HbmBudgetError` naming the largest term; the
    caller (serving.ModelRegistry) may evict LRU residents and retry.

    Charges against the budget MINUS the shared ledger's held bytes — a
    concurrently running fit's placement + workspace now counts against a
    model load exactly as resident models count against fits (the
    shared-ledger contract, docs/scheduling.md) — and reserves the admitted
    estimate there (kind "serve", released by the registry on eviction).
    `resident_bytes` remains for callers outside the registry that account
    residents themselves; the registry passes 0 (its residents already hold
    ledger reservations).

    `chip_ids` places the replica on an explicit chip set (2-D book,
    docs/scheduling.md "2-D placement"): the byte check runs against those
    chips' book only, and the reservation claims them EXCLUSIVELY — a
    4-chip serving replica co-admits beside a 4-chip fit on the other half
    of the mesh instead of serializing against it. Defaults to the ambient
    `chip_scope` pin when one is active, else the legacy whole-pool claim."""
    from . import telemetry
    from .core import config
    from .ops_plane import audit as _audit
    from .scheduler.ledger import global_ledger

    if bucket_rows_count is None:
        bucket_rows_count = int(config.get("serve_max_batch_rows", 8192))
    if tenant is None:
        # per-model serving tenants ("serving:<name>") so tenant_usage() and
        # eviction can weigh actual per-model byte-seconds instead of one
        # undifferentiated "serving" bucket; type name is the fallback when
        # the caller has no registry name for the model
        tenant = f"serving:{type(model).__name__}"
    capacity = device_capacity_bytes(devices=devices)
    budget = (
        None if capacity is None else int(capacity * (1.0 - headroom_fraction()))
    )
    led = global_ledger()
    if chip_ids is None:
        chip_ids = _claimed_chips()
    else:
        chip_ids = tuple(int(c) for c in chip_ids)
    with led.admission():
        if budget is None:
            held = 0
        elif chip_ids:
            held = max(led.reserved_bytes_on(c) for c in chip_ids)
        else:
            held = led.reserved_bytes()
        est = model_serve_estimate(model, bucket_rows_count)
        if telemetry.enabled():
            telemetry.registry().gauge("memory.serve_estimate_bytes", est.total())
        if budget is None or est.total() + int(resident_bytes) + held <= budget:
            # serving residents are shared infrastructure, accounted to a
            # per-model "serving:<name>" tenant (not whichever tenant's
            # thread loaded them)
            reservation = led.reserve(
                f"serve:{type(model).__name__}", "serve", est.total(),
                tenant=tenant, chip_ids=chip_ids,
            )
            led.note_admission(budget)
            _audit.record_decision(
                "admission", "serving", RESIDENT,
                subject=type(model).__name__, tenant=tenant,
                estimate_bytes=est.total(), budget_bytes=budget,
            )
            return AdmissionDecision(
                verdict=RESIDENT,
                estimate=est,
                capacity_bytes=capacity,
                budget_bytes=budget,
                reason="fits" if budget is not None else "no capacity information",
                reservation=reservation,
            )
        led.note_admission(budget)
        name, nbytes = est.largest()
        _audit.record_decision(
            "admission", "serving", "refused",
            subject=type(model).__name__, tenant=tenant,
            reason="over budget", estimate_bytes=est.total(),
            budget_bytes=budget, largest_term=name,
        )
        raise HbmBudgetError(
            f"{type(model).__name__} load does not fit the serving budget "
            f"({int(resident_bytes)} bytes already resident, {held} "
            "bytes/device held in the shared ledger)",
            estimate_bytes=est.total(),
            capacity_bytes=budget,
            largest_term=name,
            largest_term_bytes=nbytes,
            terms=est.terms,
        )


def release_admission(adm: Optional[AdmissionDecision]) -> None:
    """Return an admission's shared-ledger claim (idempotent; None-safe for
    `finally` blocks). No-op for job-owned admissions (their `reservation`
    is None — the scheduler releases the job's claim at job end)."""
    if adm is None or adm.reservation is None:
        return
    from .scheduler.ledger import global_ledger

    global_ledger().release(adm.reservation)
    adm.reservation = None


def rereserve_admission(adm: AdmissionDecision, owner: str = "fit:cache-hit"):
    """Shared-ledger claim for a fit served from the device-dataset scope
    CACHE (the placement physically exists; a cache hit skips `admit_fit`).
    Bookkeeping-only — no budget check: the bytes are already held, so the
    honest move is to record them, and later admissions will see them.
    Inside a scheduler job the job's reservation is resized instead and
    None is returned (job-owned)."""
    from .scheduler import context as _sched_ctx
    from .scheduler.ledger import global_ledger

    led = global_ledger()
    job = _sched_ctx.current_job()
    job_res = getattr(job, "reservation", None) if job is not None else None
    if job_res is not None:
        led.resize(job_res, adm.estimate.total())
        return None
    return led.reserve(
        owner, "fit", adm.estimate.total(), chips=getattr(adm, "chips", 1),
        chip_ids=_claimed_chips(),
    )


# ------------------------------------------------------------------ OOM -----


def is_oom_error(exc: BaseException) -> bool:
    """Whether `exc` is a backend out-of-memory failure the fit driver should
    convert to `HbmBudgetError` (and retry once out-of-core). Matches XLA's
    RESOURCE_EXHAUSTED surface (jaxlib raises it as a RuntimeError subclass)
    and plain MemoryError; an already-typed `HbmBudgetError` is NOT matched —
    it must propagate, not re-enter the conversion."""
    if isinstance(exc, HbmBudgetError):
        return False
    if not isinstance(exc, (RuntimeError, MemoryError)):
        return False
    msg = str(exc)
    return (
        "RESOURCE_EXHAUSTED" in msg
        or "out of memory" in msg.lower()
        or isinstance(exc, MemoryError)
    )


def as_hbm_budget_error(exc: BaseException) -> HbmBudgetError:
    """Wrap a caught backend OOM as the typed, permanent `HbmBudgetError`
    (no estimate attached — the backend, not the preflight, made the call)."""
    return HbmBudgetError(f"backend out-of-memory during fit: {exc}")
