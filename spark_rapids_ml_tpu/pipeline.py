#
# Pipeline / PipelineModel — the pyspark.ml.Pipeline contract for chaining
# this framework's estimators and transformers without a Spark session.
# (The reference's estimators plug into pyspark's own Pipeline; outside
# Spark that class cannot drive them, so the framework carries the minimal
# equivalent: fit chains stage-by-stage, transformers pass through, the
# fitted PipelineModel transforms in sequence and persists like
# CrossValidatorModel — a composite directory restored by class dispatch.)
#
from __future__ import annotations

from typing import Any, List, Optional

from .params import Params


def _is_estimator(stage: Any) -> bool:
    return hasattr(stage, "fit")


class Pipeline(Params):
    """Chain of stages; estimators are fit on the running transform of the
    input, transformers (fitted models) are applied as-is (pyspark.ml
    semantics: a transformer stage transforms the data seen by later
    stages).

    >>> model = Pipeline(stages=[pca, lr]).fit(df)
    >>> out = model.transform(df)
    """

    def __init__(self, stages: Optional[List[Any]] = None) -> None:
        super().__init__()
        self._stages: List[Any] = list(stages or [])

    def getStages(self) -> List[Any]:
        return self._stages

    def setStages(self, value: List[Any]) -> "Pipeline":
        self._stages = list(value)
        return self

    def copy(self, extra: Optional[dict] = None) -> "Pipeline":
        """Copy with `extra` param overrides ROUTED TO THE OWNING STAGE
        (pyspark Pipeline.copy semantics) — this is what lets
        CrossValidator(estimator=Pipeline(...)) sweep a stage's params
        through the fallback fit-per-model path."""
        extra = dict(extra or {})
        stages = []
        for s in self._stages:
            if hasattr(s, "copy") and hasattr(s, "hasParam"):
                own = {
                    p: v
                    for p, v in extra.items()
                    if s.hasParam(getattr(p, "name", str(p)))
                }
                stages.append(s.copy(own))
            else:
                stages.append(s)
        return Pipeline(stages=stages)

    def fit(self, dataset: Any) -> "PipelineModel":
        if not self._stages:
            raise ValueError("Pipeline has no stages")
        df = dataset
        fitted: List[Any] = []
        for i, stage in enumerate(self._stages):
            if _is_estimator(stage):
                model = stage.fit(df)
            elif hasattr(stage, "transform"):
                model = stage
            else:
                raise TypeError(f"stage {i} ({type(stage).__name__}) is neither estimator nor transformer")
            fitted.append(model)
            if i < len(self._stages) - 1:  # the last stage's output is unused
                df = model.transform(df)
        return PipelineModel(stages=fitted)


class PipelineModel(Params):
    def __init__(self, stages: Optional[List[Any]] = None) -> None:
        super().__init__()
        self.stages: List[Any] = list(stages or [])

    def transform(self, dataset: Any):
        df = dataset
        for stage in self.stages:
            df = stage.transform(df)
        return df

    # persistence: composite directory, one sub-save per stage (the same
    # shape as CrossValidatorModel), restored by class dispatch
    def write(self) -> "_PipelineModelWriter":
        return _PipelineModelWriter(self)

    def save(self, path: str) -> None:
        self.write().save(path)

    @classmethod
    def load(cls, path: str) -> "PipelineModel":
        import json
        import os

        from .core import load_instance

        with open(os.path.join(path, "metadata.json")) as f:
            meta = json.load(f)
        stages = [
            load_instance(os.path.join(path, f"stage{i}"))
            for i in range(meta["numStages"])
        ]
        return cls(stages=stages)


class _PipelineModelWriter:
    def __init__(self, instance: PipelineModel) -> None:
        self.instance = instance
        self._overwrite = False

    def overwrite(self) -> "_PipelineModelWriter":
        self._overwrite = True
        return self

    def save(self, path: str) -> None:
        import json
        import os

        from .core import _prepare_save_path

        inst = self.instance
        if not inst.stages:
            raise ValueError("PipelineModel has no stages to save")
        _prepare_save_path(path, self._overwrite)
        meta = {
            "class": f"{type(inst).__module__}.{type(inst).__qualname__}",
            "numStages": len(inst.stages),
        }
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(meta, f, indent=2)
        for i, stage in enumerate(inst.stages):
            stage.write().overwrite().save(os.path.join(path, f"stage{i}"))
