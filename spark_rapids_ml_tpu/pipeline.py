#
# Pipeline / PipelineModel — the pyspark.ml.Pipeline contract for chaining
# this framework's estimators and transformers without a Spark session.
# (The reference's estimators plug into pyspark's own Pipeline; outside
# Spark that class cannot drive them, so the framework carries the minimal
# equivalent: fit chains stage-by-stage, transformers pass through, the
# fitted PipelineModel transforms in sequence and persists like
# CrossValidatorModel — a composite directory restored by class dispatch.)
#
from __future__ import annotations

from typing import Any, List, Optional

from .params import Params


def _is_estimator(stage: Any) -> bool:
    return hasattr(stage, "fit")


class Pipeline(Params):
    """Chain of stages; estimators are fit on the running transform of the
    input, transformers (fitted models) are applied as-is (pyspark.ml
    semantics: a transformer stage transforms the data seen by later
    stages).

    >>> model = Pipeline(stages=[pca, lr]).fit(df)
    >>> out = model.transform(df)
    """

    def __init__(self, stages: Optional[List[Any]] = None) -> None:
        super().__init__()
        self._stages: List[Any] = list(stages or [])

    def getStages(self) -> List[Any]:
        return self._stages

    def setStages(self, value: List[Any]) -> "Pipeline":
        self._stages = list(value)
        return self

    def copy(self, extra: Optional[dict] = None) -> "Pipeline":
        """Copy with `extra` param overrides routed to the stage that owns
        each param — this is what lets CrossValidator / TrainValidationSplit
        sweep a stage's params through the fallback fit-per-model path.

        Param objects here are per-NAME singletons (mixin class attributes),
        so a name carried by MORE THAN ONE stage cannot identify its target:
        that case raises instead of silently re-tuning every matching stage
        (pyspark disambiguates via per-instance parent uids; this framework
        keeps the simpler Param model and makes the ambiguity loud)."""
        extra = dict(extra or {})
        routable = [
            s if (hasattr(s, "copy") and hasattr(s, "hasParam")) else None
            for s in self._stages
        ]
        per_stage: List[dict] = [{} for _ in self._stages]
        for p, v in extra.items():
            name = getattr(p, "name", str(p))
            owners = [i for i, s in enumerate(routable) if s is not None and s.hasParam(name)]
            if not owners:
                # silently dropping a no-owner param would let a typo'd key in
                # a CV/TVS grid train identical models — as loud as the
                # ambiguous-owner case below
                raise ValueError(
                    f"param {name!r} is carried by no stage of this Pipeline — "
                    "a typo'd or wrong-estimator key in a tuning grid would "
                    "otherwise be silently ignored"
                )
            if len(owners) > 1:
                raise ValueError(
                    f"param {name!r} is carried by stages {owners}; tuning it through "
                    "a Pipeline is ambiguous — set it on the intended stage directly"
                )
            per_stage[owners[0]][p] = v
        return Pipeline(
            stages=[
                s.copy(per_stage[i]) if routable[i] is not None else s
                for i, s in enumerate(self._stages)
            ]
        )

    def fit(self, dataset: Any) -> "PipelineModel":
        if not self._stages:
            raise ValueError("Pipeline has no stages")
        for i, stage in enumerate(self._stages):
            if not (_is_estimator(stage) or hasattr(stage, "transform")):
                raise TypeError(
                    f"stage {i} ({type(stage).__name__}) is neither estimator nor transformer"
                )
        # pyspark semantics: transform only feeds LATER ESTIMATORS — stop
        # running the data forward past the last estimator stage
        last_est = max(
            (i for i, s in enumerate(self._stages) if _is_estimator(s)), default=-1
        )
        df = dataset
        fitted: List[Any] = []
        for i, stage in enumerate(self._stages):
            model = stage.fit(df) if _is_estimator(stage) else stage
            fitted.append(model)
            if i < last_est:
                df = model.transform(df)
        return PipelineModel(stages=fitted)


class PipelineModel(Params):
    def __init__(self, stages: Optional[List[Any]] = None) -> None:
        super().__init__()
        self.stages: List[Any] = list(stages or [])

    def transform(self, dataset: Any):
        df = dataset
        for stage in self.stages:
            df = stage.transform(df)
        return df

    # persistence: composite directory, one sub-save per stage (the shared
    # CompositeWriter protocol), restored by class dispatch
    def write(self):
        from .core import CompositeWriter

        if not self.stages:
            raise ValueError("PipelineModel has no stages to save")
        return CompositeWriter(
            self,
            build_meta=lambda inst: {"numStages": len(inst.stages)},
            iter_children=lambda inst: (
                (f"stage{i}", s) for i, s in enumerate(inst.stages)
            ),
        )

    def save(self, path: str) -> None:
        self.write().save(path)

    @classmethod
    def load(cls, path: str) -> "PipelineModel":
        import json
        import os

        from .core import load_instance

        with open(os.path.join(path, "metadata.json")) as f:
            meta = json.load(f)
        stages = [
            load_instance(os.path.join(path, f"stage{i}"))
            for i in range(meta["numStages"])
        ]
        return cls(stages=stages)
