#
# Evaluators — drop-in for `pyspark.ml.evaluation.{RegressionEvaluator,
# MulticlassClassificationEvaluator, BinaryClassificationEvaluator}`.
#
# The reference consumes the pyspark evaluators directly and only translates
# them into sufficient-stats requests (reference core.py:1333-1432,
# classification.py:157-276); since pyspark is optional here, the evaluator
# classes live in-tree with the same Param surface. `evaluate(dataset)` also
# works standalone on any DataFrame-like input.
#
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from .data import as_pandas
from .metrics import MulticlassMetrics, RegressionMetrics
from .params import (
    HasLabelCol,
    HasPredictionCol,
    HasProbabilityCol,
    HasRawPredictionCol,
    HasWeightCol,
    Param,
    Params,
    TypeConverters,
)


class Evaluator(Params):
    def evaluate(self, dataset: Any) -> float:
        raise NotImplementedError

    def isLargerBetter(self) -> bool:
        return True


class RegressionEvaluator(Evaluator, HasLabelCol, HasPredictionCol, HasWeightCol):
    """metricName in rmse|mse|r2|mae|var."""

    metricName = Param("metricName", "metric name in evaluation (rmse|mse|r2|mae|var)", TypeConverters.toString)
    throughOrigin = Param("throughOrigin", "whether regression is through the origin", TypeConverters.toBoolean)

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(metricName="rmse", throughOrigin=False)
        self._set(**kwargs)

    def getMetricName(self) -> str:
        return self.getOrDefault("metricName")

    def setMetricName(self, value: str) -> "RegressionEvaluator":
        return self._set(metricName=value)

    def setLabelCol(self, value: str) -> "RegressionEvaluator":
        return self._set(labelCol=value)

    def setPredictionCol(self, value: str) -> "RegressionEvaluator":
        return self._set(predictionCol=value)

    def isLargerBetter(self) -> bool:
        return self.getMetricName() in ("r2", "var")

    def evaluate(self, dataset: Any) -> float:
        pdf = as_pandas(dataset)
        label = pdf[self.getOrDefault("labelCol")].to_numpy(dtype=np.float64)
        prediction = pdf[self.getOrDefault("predictionCol")].to_numpy(dtype=np.float64)
        weight = (
            pdf[self.getOrDefault("weightCol")].to_numpy(dtype=np.float64)
            if self.isDefined("weightCol")
            else None
        )
        return RegressionMetrics.from_values(label, prediction, weight).evaluate(self)


class MulticlassClassificationEvaluator(
    Evaluator, HasLabelCol, HasPredictionCol, HasProbabilityCol, HasWeightCol
):
    metricName = Param(
        "metricName",
        "metric name in evaluation "
        "(f1|accuracy|weightedPrecision|weightedRecall|weightedTruePositiveRate|"
        "weightedFalsePositiveRate|weightedFMeasure|truePositiveRateByLabel|"
        "falsePositiveRateByLabel|precisionByLabel|recallByLabel|fMeasureByLabel|"
        "logLoss|hammingLoss)",
        TypeConverters.toString,
    )
    metricLabel = Param("metricLabel", "the class whose metric will be computed", TypeConverters.toFloat)
    beta = Param("beta", "beta value in weightedFMeasure|fMeasureByLabel", TypeConverters.toFloat)
    eps = Param("eps", "log-loss clamp epsilon", TypeConverters.toFloat)

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(metricName="f1", metricLabel=0.0, beta=1.0, eps=1e-15)
        self._set(**kwargs)

    def getMetricName(self) -> str:
        return self.getOrDefault("metricName")

    def setMetricName(self, value: str) -> "MulticlassClassificationEvaluator":
        return self._set(metricName=value)

    def setLabelCol(self, value: str) -> "MulticlassClassificationEvaluator":
        return self._set(labelCol=value)

    def setPredictionCol(self, value: str) -> "MulticlassClassificationEvaluator":
        return self._set(predictionCol=value)

    def getMetricLabel(self) -> float:
        return self.getOrDefault("metricLabel")

    def getBeta(self) -> float:
        return self.getOrDefault("beta")

    def isLargerBetter(self) -> bool:
        return self.getMetricName() not in (
            "weightedFalsePositiveRate",
            "falsePositiveRateByLabel",
            "logLoss",
            "hammingLoss",
        )

    def evaluate(self, dataset: Any) -> float:
        pdf = as_pandas(dataset)
        label = pdf[self.getOrDefault("labelCol")].to_numpy(dtype=np.float64)
        prediction = pdf[self.getOrDefault("predictionCol")].to_numpy(dtype=np.float64)
        weight = (
            pdf[self.getOrDefault("weightCol")].to_numpy(dtype=np.float64)
            if self.isDefined("weightCol")
            else np.ones_like(label)
        )
        # vectorized weighted confusion counts over unique (label, prediction) pairs
        pairs = np.stack([label, prediction], axis=1)
        uniq, inverse = np.unique(pairs, axis=0, return_inverse=True)
        counts = np.bincount(inverse, weights=weight, minlength=len(uniq))
        confusion: Dict = {
            (float(uniq[i, 0]), float(uniq[i, 1])): float(counts[i]) for i in range(len(uniq))
        }
        log_loss = None
        if self.getMetricName() == "logLoss":
            prob_col = self.getOrDefault("probabilityCol")
            probs = np.stack([np.asarray(p) for p in pdf[prob_col]])
            eps = self.getOrDefault("eps")
            # resolve each label to its probability-vector column: direct index
            # when labels are already 0..k-1 (the Spark convention), otherwise
            # by position among the sorted class values — matching how models
            # order probability columns by classes_. The fallback needs every
            # class present in this dataset; a partial batch with exotic labels
            # is ambiguous, so raise rather than silently mis-index.
            lab_int = label.astype(int)
            if np.array_equal(lab_int, label) and lab_int.min() >= 0 and lab_int.max() < probs.shape[1]:
                col = lab_int
            else:
                classes = np.unique(label)
                if len(classes) != probs.shape[1]:
                    raise ValueError(
                        "logLoss cannot map labels to probability columns: labels are not "
                        f"0..{probs.shape[1] - 1} indices and the {len(classes)} distinct label "
                        f"values do not cover the {probs.shape[1]} probability columns"
                    )
                col = np.searchsorted(classes, label)
            p_true = np.clip(probs[np.arange(len(label)), col], eps, 1 - eps)
            log_loss = float(np.sum(-np.log(p_true) * weight))
        return MulticlassMetrics.from_confusion(confusion, log_loss).evaluate(self)


class BinaryClassificationEvaluator(Evaluator, HasLabelCol, HasRawPredictionCol, HasWeightCol):
    """metricName in areaUnderROC|areaUnderPR (computed from raw scores)."""

    metricName = Param("metricName", "metric name in evaluation (areaUnderROC|areaUnderPR)", TypeConverters.toString)
    numBins = Param("numBins", "number of bins for curve computation (0 = exact)", TypeConverters.toInt)

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._setDefault(metricName="areaUnderROC", numBins=1000)
        self._set(**kwargs)

    def getMetricName(self) -> str:
        return self.getOrDefault("metricName")

    def setMetricName(self, value: str) -> "BinaryClassificationEvaluator":
        return self._set(metricName=value)

    def setLabelCol(self, value: str) -> "BinaryClassificationEvaluator":
        return self._set(labelCol=value)

    def setRawPredictionCol(self, value: str) -> "BinaryClassificationEvaluator":
        return self._set(rawPredictionCol=value)

    def evaluate(self, dataset: Any) -> float:
        pdf = as_pandas(dataset)
        label = pdf[self.getOrDefault("labelCol")].to_numpy(dtype=np.float64)
        raw = pdf[self.getOrDefault("rawPredictionCol")]
        first = raw.iloc[0]
        if np.ndim(first) > 0 or isinstance(first, (list, np.ndarray)) or hasattr(first, "toArray"):
            score = np.stack([np.asarray(v.toArray() if hasattr(v, "toArray") else v) for v in raw])[:, -1]
        else:
            score = raw.to_numpy(dtype=np.float64)
        weight = (
            pdf[self.getOrDefault("weightCol")].to_numpy(dtype=np.float64)
            if self.isDefined("weightCol")
            else np.ones_like(label)
        )
        order = np.argsort(-score, kind="stable")
        score, label, weight = score[order], label[order], weight[order]
        tp_row = np.cumsum(weight * (label > 0.5))
        fp_row = np.cumsum(weight * (label <= 0.5))
        # group tied scores: one ROC/PR point per unique threshold, taken at the
        # LAST row of each tie group (counting the whole group at once)
        is_last_of_group = np.append(score[1:] != score[:-1], True)
        tp = tp_row[is_last_of_group]
        fp = fp_row[is_last_of_group]
        num_bins = self.getOrDefault("numBins")
        if num_bins and len(tp) > num_bins:
            # downsample curve points (Spark's numBins behavior), keeping the end
            keep = np.unique(np.concatenate([
                np.linspace(0, len(tp) - 1, num_bins).astype(int), [len(tp) - 1]
            ]))
            tp, fp = tp[keep], fp[keep]
        tot_p, tot_n = tp_row[-1], fp_row[-1]
        if self.getMetricName() == "areaUnderROC":
            tpr = np.concatenate([[0.0], tp / max(tot_p, 1e-30)])
            fpr = np.concatenate([[0.0], fp / max(tot_n, 1e-30)])
            return float(np.trapezoid(tpr, fpr))
        if self.getMetricName() == "areaUnderPR":
            precision = tp / np.maximum(tp + fp, 1e-30)
            recall = tp / max(tot_p, 1e-30)
            recall = np.concatenate([[0.0], recall])
            precision = np.concatenate([[1.0], precision])
            return float(np.trapezoid(precision, recall))
        raise ValueError(f"Unsupported metric name {self.getMetricName()!r}")
