#
# FitScheduler: the multi-tenant fit queue (docs/scheduling.md).
#
# The reference gets multi-job behavior for free from Spark's stage-level
# scheduler (PAPER.md L1/L5: the driver queues barrier stages against a
# shared executor pool); this stack runs fits as single-controller programs
# against one mesh, so a production service with many tenants needs its own
# scheduling layer. This module composes three things earlier layers built:
#
#   * the HBM budgeter's per-fit byte estimates (memory.resident_estimate /
#     streaming_estimate) become the BIN-PACKING input: jobs whose
#     placements + workspaces fit the shared `HbmLedger` together are
#     CO-ADMITTED and run concurrently; the rest queue in priority order;
#   * the checkpoint store (checkpoint.CheckpointStore) becomes PREEMPTION:
#     a high-priority job that doesn't fit evicts the lowest-priority
#     running fit at its next segment boundary (the cooperative flag in
#     scheduler/context.py, checked where the solvers already host-fetch);
#     the preempted fit's `SolverCheckpoint` persists in the job-owned
#     store, its reservation frees immediately, and a later re-admission
#     resumes bit-identically on the same mesh;
#   * admission demotion gives DEGRADED-MODE service: a job preempted
#     `config["sched_max_preemptions"]` times is demoted to the out-of-core
#     streaming path — a floor-chunk footprint that packs into almost any
#     budget, so chronically displaced tenants make progress instead of
#     starving (estimators without a streaming path become non-preemptible
#     instead: they run to completion once admitted).
#
# Scheduling passes run on submit and on every job transition (no dispatcher
# thread); each pass scans the priority-ordered queue first-fit under the
# ledger's admission lock, stops backfilling while a preemption is pending
# for a blocked higher-priority job (space is coming — filling it with
# lower-priority work would re-starve the blocked job), and otherwise
# backfills smaller jobs into the remaining budget (bin-packing).
#
# Preemption requires a checkpoint cadence: with
# ``config["checkpoint_every_iters"] == 0`` solvers never reach a boundary,
# so running fits are effectively non-preemptible and high-priority jobs
# wait for completions (documented in docs/scheduling.md "Fairness knobs").
#
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ..errors import PreemptedError, SchedulerSaturatedError
from ..ops_plane import audit as _audit
from ..ops_plane import slo as _slo
from ..utils import get_logger, lockcheck
from .context import job_scope
from .ledger import HbmLedger, global_ledger

__all__ = ["FitJob", "FitScheduler"]

_STATES = ("queued", "running", "preempted", "completed", "failed", "refused")


class FitJob:
    """One submitted fit: the estimator/dataset pair, its tenant + priority,
    the job-owned `CheckpointStore` (survives preemptions — the resume
    substrate), and a future-like result surface (`result()` / `done()`).

    `stats()` is the per-tenant telemetry the scheduler stamps into the
    finished model's ``_fit_metrics["scheduler"]`` — queue wait, preemption
    and resume counts, demotion, and the job's HBM share at admission."""

    def __init__(
        self,
        job_id: int,
        estimator: Any,
        dataset: Any,
        tenant: str,
        priority: int,
        warm_start_from: Any = None,
    ) -> None:
        from .. import checkpoint as _ckpt

        self.job_id = int(job_id)
        self.estimator = estimator
        self.dataset = dataset
        self.tenant = str(tenant)
        self.priority = int(priority)
        self.warm_start_from = warm_start_from
        # job-owned checkpoint store: installed around every run attempt via
        # checkpoint_scope(store=...), so the solver checkpoints a preemption
        # leaves behind are exactly what the resumed attempt restores
        self.store = _ckpt.CheckpointStore()
        self.state = "queued"
        self.preemptions = 0
        self.resumes = 0
        self.demoted = False
        self.demote_to_stream = False
        self.reservation: Any = None  # ledger HbmReservation while admitted
        self.admitted_bytes = 0
        self.hbm_share = 0.0
        self.queue_wait_s = 0.0
        self.run_s = 0.0
        self._wait_since = time.monotonic()
        self._run_since: Optional[float] = None
        # byte estimates (filled by the scheduler's preflight), and the
        # device count they span — the chip-seconds multiplier for the
        # ledger's per-tenant accounting
        self.resident_estimate: Any = None
        self.stream_floor_estimate: Any = None
        self.chips = 1
        # 2-D placement (docs/scheduling.md "2-D placement"): which chips
        # the admitted reservation owns, and the matching device objects the
        # runner pins via parallel.mesh.chip_scope — None when the scheduler
        # runs in the legacy bytes-only mode (config["sched_chip_placement"])
        self.chip_ids: Any = None
        self.placed_devices: Any = None
        self._preempt = threading.Event()
        self._preempt_reason = ""
        self._done = threading.Event()
        self._model: Any = None
        self._error: Optional[BaseException] = None

    # ------------------------------------------------------------ future --
    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> Any:
        """Block until the job finishes; returns the fitted model or raises
        the job's failure (including `SchedulerSaturatedError` refusals and
        shutdown). The model's ``_fit_metrics["scheduler"]`` carries this
        job's per-tenant telemetry."""
        if not self._done.wait(timeout):  # blocking-ok: caller-bounded result wait (timeout passed through)
            raise TimeoutError(
                f"job {self.job_id} ({self.tenant!r}) not done within {timeout}s"
            )
        if self._error is not None:
            raise self._error
        return self._model

    # -------------------------------------------------------- preemption --
    def request_preempt(self, reason: str) -> None:
        self._preempt_reason = reason
        self._preempt.set()

    def preempt_requested(self) -> bool:
        return self._preempt.is_set()

    def check_preempt(self, solver: str, iteration: int) -> None:
        """The cooperative yield point (`scheduler.context.preemption_point`
        delegates here): raises `PreemptedError` when flagged. Called only
        at checkpoint-cadence boundaries, AFTER the boundary checkpoint
        saved — unwinding here loses zero work."""
        if self._preempt.is_set():
            raise PreemptedError(
                self.job_id,
                solver=solver,
                iteration=iteration,
                reason=self._preempt_reason,
            )

    # ------------------------------------------------------------- stats --
    def stats(self) -> Dict[str, Any]:
        return {
            "job_id": self.job_id,
            "tenant": self.tenant,
            "priority": self.priority,
            "state": self.state,
            "queue_wait_s": self.queue_wait_s,
            "run_s": self.run_s,
            "preemptions": self.preemptions,
            "resumes": self.resumes,
            "demoted": self.demoted,
            "admitted_bytes": self.admitted_bytes,
            "hbm_share": self.hbm_share,
            "chips": self.chips,
            "chip_ids": list(self.chip_ids) if self.chip_ids is not None else None,
        }

    def _finish(self, model: Any) -> None:
        self.state = "completed"
        self._model = model
        self._done.set()

    def _fail(self, exc: BaseException) -> None:
        self.state = "failed" if not isinstance(exc, SchedulerSaturatedError) else "refused"
        self._error = exc
        self._done.set()


class FitScheduler:
    """Priority job queue with bin-packed co-admission and checkpoint
    preemption over the shared `HbmLedger` (module docstring,
    docs/scheduling.md).

    ``submit(estimator, dataset, tenant=, priority=)`` returns a `FitJob`
    future. Higher `priority` values run first; ties are FIFO. Jobs run on
    worker threads — one per admitted job — so co-admitted fits genuinely
    overlap; callers wanting collective-free concurrency on a shared mesh
    should submit single-device estimators (``est.num_workers = 1``)."""

    def __init__(
        self,
        *,
        ledger: Optional[HbmLedger] = None,
        max_concurrent: Optional[int] = None,
        max_preemptions: Optional[int] = None,
        chip_placement: Optional[bool] = None,
    ) -> None:
        from ..core import config

        self._ledger = ledger if ledger is not None else global_ledger()
        # 2-D placement mode: claims name WHICH chips (contiguous first-fit
        # runs) and jobs run pinned to them via chip_scope, so two half-mesh
        # fits genuinely overlap instead of time-slicing the whole mesh
        self._chip_placement = bool(
            chip_placement
            if chip_placement is not None
            else config.get("sched_chip_placement", False)
        )
        self._max_concurrent = int(
            max_concurrent
            if max_concurrent is not None
            else config.get("sched_max_concurrent", 4)
        )
        self._max_preemptions = int(
            max_preemptions
            if max_preemptions is not None
            else config.get("sched_max_preemptions", 2)
        )
        self._lock = lockcheck.make_lock("scheduler.queue.FitScheduler._lock", "rlock")
        self._queue: List[FitJob] = []  # guarded-by: _lock
        self._running: Dict[int, FitJob] = {}  # guarded-by: _lock
        self._threads: List[threading.Thread] = []  # guarded-by: _lock
        self._jobs: List[FitJob] = []  # guarded-by: _lock
        self._next_id = 1  # guarded-by: _lock
        self._closed = False  # guarded-by: _lock
        self._logger = get_logger(type(self))
        # opt-in live scrape surface (SRML_METRICS_PORT): a long-lived
        # scheduler is exactly the process an operator wants /metrics on
        from ..ops_plane import ensure_server

        ensure_server()

    # ------------------------------------------------------------ submit --
    def submit(
        self,
        estimator: Any,
        dataset: Any,
        *,
        tenant: str = "default",
        priority: int = 0,
        warm_start_from: Any = None,
    ) -> FitJob:
        """Queue one fit. Returns immediately with a `FitJob` future.

        Raises `SchedulerSaturatedError` — the typed refusal mirroring
        `HbmBudgetError` — when the job's SMALLEST possible footprint (the
        streaming floor, or the resident estimate for estimators with no
        out-of-core path) exceeds the whole budget: no amount of queueing or
        preemption can ever place it, so the tenant learns at submit time."""
        from .. import telemetry

        with self._lock:
            if self._closed:
                raise RuntimeError("FitScheduler is shut down")
            job = FitJob(
                self._next_id, estimator, dataset,
                tenant, priority, warm_start_from,
            )
            self._next_id += 1
            # registered BEFORE preflight: a refused job must still show up
            # in stats()'s per-tenant roll-up (state "refused")
            self._jobs.append(job)
        self._preflight(job)  # may raise SchedulerSaturatedError (typed refusal)
        reg = telemetry.registry()
        reg.inc("scheduler.jobs_submitted")
        with self._lock:
            self._queue.append(job)
            self._schedule_locked()
            if job.state == "queued":
                reg.inc("scheduler.jobs_queued")
        return job

    def _preflight(self, job: FitJob) -> None:
        """Byte estimates for bin-packing (the PR-7 budgeter's formulas are
        the input — docs/scheduling.md "Co-admission"), plus the
        cannot-ever-fit refusal. The extraction is host-side column
        selection only; the extracted blocks are dropped after estimating
        (the fit re-extracts — holding every queued job's dataset twice
        would defeat the memory plane)."""
        from .. import memory, telemetry
        from ..parallel.mesh import default_devices

        est = job.estimator
        extracted = est._pre_process_data(job.dataset, for_fit=True, defer_validation=True)
        n_dev = max(1, min(int(est.num_workers), len(default_devices())))
        job.chips = n_dev
        job.resident_estimate = memory.resident_estimate(est, extracted, n_dev)
        if getattr(est, "_supports_streaming_fit", False):
            floor = min(memory.MIN_STREAM_CHUNK_ROWS, max(1, int(extracted.n_rows)))
            job.stream_floor_estimate = memory.streaming_estimate(
                est, extracted, n_dev, floor
            )
        budget = self._budget()
        minimal = (
            job.stream_floor_estimate
            if job.stream_floor_estimate is not None
            else job.resident_estimate
        )
        if budget is not None and minimal.total() > budget:
            name, nbytes = minimal.largest()
            exc = SchedulerSaturatedError(
                f"job for tenant {job.tenant!r} "
                f"({type(est).__name__}) cannot ever be scheduled: its "
                "smallest working set exceeds the whole budget",
                tenant=job.tenant,
                estimate_bytes=minimal.total(),
                budget_bytes=budget,
                largest_term=name,
                largest_term_bytes=nbytes,
                terms=minimal.terms,
            )
            telemetry.registry().inc("scheduler.jobs_refused")
            _audit.record_decision(
                "admission", "scheduler", "refused",
                subject=f"job:{job.job_id}", tenant=job.tenant,
                reason=str(exc), estimate_bytes=minimal.total(),
                budget_bytes=budget,
            )
            job._fail(exc)
            raise exc

    # -------------------------------------------------------- scheduling --
    def _budget(self) -> Optional[int]:
        from .. import memory
        from ..parallel.mesh import default_devices

        capacity = memory.device_capacity_bytes(
            devices=default_devices(), consume_chaos=False
        )
        if capacity is None:
            return None
        return int(capacity * (1.0 - memory.headroom_fraction()))

    def _need_bytes(self, job: FitJob, budget: Optional[int]) -> int:
        """The bytes this job's NEXT admission will claim: the streaming
        floor once demoted (or when the resident set alone exceeds the
        budget — the fit's own admission would demote it anyway), else the
        resident estimate."""
        resident = job.resident_estimate.total()
        if job.stream_floor_estimate is not None and (
            job.demote_to_stream or (budget is not None and resident > budget)
        ):
            return int(job.stream_floor_estimate.total())
        return int(resident)

    def _chip_pool(self) -> List[Any]:
        from ..parallel.mesh import default_devices

        return list(default_devices())

    @staticmethod
    def _chip_id(device: Any, index: int) -> int:
        return int(getattr(device, "id", index))

    def _place_job_locked(
        self, job: FitJob, need: int, budget: Optional[int], pool: List[Any]
    ) -> Optional[Any]:
        """2-D admission for one job (caller holds the ledger's admission
        lock): first-fit over CONTIGUOUS chip runs of the job's width, in
        pool order. `try_reserve(chip_ids=...)` is the 2-D check — occupancy
        exclusivity plus per-chip bytes — so a window that fails either
        dimension just slides right. Returns the reservation (with the
        chosen chips recorded on the job) or None when no run fits."""
        width = max(1, min(int(job.chips), len(pool)))
        for start in range(0, len(pool) - width + 1):
            window = pool[start:start + width]
            chip_ids = [self._chip_id(d, start + i) for i, d in enumerate(window)]
            r = self._ledger.try_reserve(
                f"job:{job.job_id}:{job.tenant}", "job", need,
                budget=budget, tenant=job.tenant, chip_ids=chip_ids,
            )
            if r is not None:
                job.chip_ids = tuple(chip_ids)
                job.placed_devices = list(window)
                return r
        return None

    def _schedule_locked(self) -> None:
        """One co-admission pass (caller holds `self._lock`): first-fit over
        the priority-ordered queue under the ledger's admission lock, with
        preemption for a blocked higher-priority head and bin-packing
        backfill otherwise. In 2-D placement mode the first-fit is over
        contiguous chip runs as well as bytes, so jobs of disjoint widths
        co-admit onto disjoint chip sets instead of queueing."""
        from .. import telemetry

        if self._closed:
            return
        budget = self._budget()
        self._queue.sort(key=lambda j: (-j.priority, j.job_id))  # FIFO tiebreak
        reg = telemetry.registry()
        to_start: List[FitJob] = []
        pool = self._chip_pool() if self._chip_placement else []
        self._ledger.note_chip_pool(len(pool) if self._chip_placement else None)
        with self._ledger.admission():
            for job in list(self._queue):
                if len(self._running) + len(to_start) >= self._max_concurrent:
                    break
                need = self._need_bytes(job, budget)
                if self._chip_placement:
                    r = self._place_job_locked(job, need, budget, pool)
                else:
                    r = self._ledger.try_reserve(
                        f"job:{job.job_id}:{job.tenant}", "job", need,
                        budget=budget, tenant=job.tenant, chips=job.chips,
                    )
                self._ledger.note_admission(budget)
                if r is not None:
                    job.reservation = r
                    job.admitted_bytes = need
                    job.hbm_share = (need / budget) if budget else 0.0
                    to_start.append(job)
                    continue
                # blocked: the highest-priority job that doesn't fit may
                # preempt; while its preemption is pending, do NOT backfill
                # (filling the space it is waiting for would starve it)
                if self._maybe_preempt_locked(job, need, budget):
                    break
                if any(v.preempt_requested() for v in self._running.values()):
                    break
                # no victim to preempt: keep scanning — a smaller job lower
                # in the queue may still bin-pack into the remaining budget
        now = time.monotonic()
        if to_start:
            # a long-lived scheduler must not accumulate finished worker
            # threads; live ones stay joinable for shutdown(wait=True)
            self._threads = [t for t in self._threads if t.is_alive()]
        for job in to_start:
            self._queue.remove(job)
            wait = now - job._wait_since
            job.queue_wait_s += wait
            reg.inc("scheduler.jobs_admitted")
            reg.observe("scheduler.queue_wait_s", wait)
            reg.observe("scheduler.hbm_share", job.hbm_share)
            _audit.record_decision(
                "admission", "scheduler",
                "resumed" if job.state == "preempted" else "admitted",
                subject=f"job:{job.job_id}", tenant=job.tenant,
                priority=job.priority, admitted_bytes=job.admitted_bytes,
                queue_wait_s=round(wait, 6),
            )
            if job.state == "preempted":
                job.resumes += 1
                reg.inc("scheduler.jobs_resumed")
            job.state = "running"
            job._run_since = now
            self._running[job.job_id] = job
            t = threading.Thread(
                target=self._run_job, args=(job,),
                name=f"srml-sched-job-{job.job_id}", daemon=True,
            )
            self._threads.append(t)
            t.start()
        if to_start:
            # queue-wait histograms were just recorded: the SLO monitors'
            # inline evaluation point (throttled; no-op without specs)
            _slo.maybe_evaluate()

    def _maybe_preempt_locked(
        self, job: FitJob, need: int, budget: Optional[int]
    ) -> bool:
        """Request preemption of the lowest-priority running fit when that
        can actually make room for `job`. Returns whether a preemption is
        now pending for it. One victim at a time: the pass re-runs when the
        victim unwinds, and escalates only if still blocked.

        With no checkpoint cadence (``config["checkpoint_every_iters"] ==
        0``) solvers never reach a yield point, so a requested preemption
        could never be observed — and its pending flag would halt ALL
        backfill for the victim's whole runtime. Don't request: running
        fits are non-preemptible then (docs/scheduling.md "Fairness
        knobs"), high-priority jobs wait for completions, and smaller jobs
        keep bin-packing."""
        from .. import checkpoint as _ckpt

        if budget is None or _ckpt.every_iters() <= 0:
            return False
        victims = [
            v for v in self._running.values() if v.priority < job.priority
        ]
        if not victims:
            return False
        freeable = sum(
            v.reservation.nbytes for v in victims if v.reservation is not None
        )
        held = self._ledger.reserved_bytes()
        if held - freeable + need > budget:
            return False  # even evicting every lower-priority fit cannot make room
        if self._chip_placement and not self._chips_freeable_locked(job, victims):
            return False  # room in bytes but not in chips: non-victim claims
            # (serving replicas, higher-priority fits) pin every contiguous
            # run of the job's width, so eviction cannot place it either
        pending = [v for v in victims if v.preempt_requested()]
        if pending:
            return True  # already waiting on a boundary
        victim = min(victims, key=lambda v: (v.priority, -v.job_id))
        self._logger.info(
            "preempting job %d (tenant %r, priority %d) for job %d "
            "(tenant %r, priority %d)",
            victim.job_id, victim.tenant, victim.priority,
            job.job_id, job.tenant, job.priority,
        )
        victim.request_preempt(
            f"higher-priority job {job.job_id} (tenant {job.tenant!r}) "
            "needs the reservation"
        )
        _audit.record_decision(
            "preemption", "scheduler", "requested",
            subject=f"job:{victim.job_id}", tenant=victim.tenant,
            reason=victim._preempt_reason, victim_priority=victim.priority,
            for_job=job.job_id, for_tenant=job.tenant,
            for_priority=job.priority,
        )
        return True

    def _chips_freeable_locked(self, job: FitJob, victims: List[FitJob]) -> bool:
        """Chip-dimension half of the preemption feasibility check: after
        evicting every lower-priority victim, does a contiguous run of the
        job's width open up? Occupancy held by NON-victims (serving
        replicas, equal/higher-priority fits) stays pinned."""
        victim_chips = set()
        for v in victims:
            if v.reservation is not None and v.reservation.chip_ids is not None:
                victim_chips.update(v.reservation.chip_ids)
        pinned = self._ledger.occupied_chips() - victim_chips
        pool = self._chip_pool()
        width = max(1, min(int(job.chips), len(pool)))
        run = 0
        for i, d in enumerate(pool):
            run = 0 if self._chip_id(d, i) in pinned else run + 1
            if run >= width:
                return True
        return False

    # ----------------------------------------------------------- running --
    def _run_job(self, job: FitJob) -> None:
        """Worker-thread body: the whole fit inside `job_scope` (so
        `memory.admit_fit` trues up the job's reservation and the solvers
        see the preemption flag) and the job-owned checkpoint store (so a
        preempted attempt's checkpoints survive into the resume)."""
        from .. import checkpoint as _ckpt
        from .. import telemetry

        import contextlib

        from ..parallel.mesh import chip_scope

        reg = telemetry.registry()
        requeue = False
        # 2-D placement: the fit sees ONLY its claimed chips — every
        # downstream mesh/placement/capacity call lands on the claimed
        # sub-mesh, so co-admitted jobs genuinely overlap on disjoint chips
        pin = (
            chip_scope(job.placed_devices)
            if job.placed_devices
            else contextlib.nullcontext()
        )
        try:
            with pin, job_scope(job), _ckpt.checkpoint_scope(store=job.store):
                if job.warm_start_from is not None:
                    model = job.estimator.fit(
                        job.dataset, warm_start_from=job.warm_start_from
                    )
                else:
                    model = job.estimator.fit(job.dataset)
            # per-tenant scheduler telemetry rides the job result — always,
            # like the admission stamp: WHY a fit waited/preempted/streamed
            # is robustness state, not a metric (the _fit_metrics dict is
            # shared across a fit's models, so stamp a copy)
            job.state = "completed"
            metrics = dict(getattr(model, "_fit_metrics", {}) or {})
            metrics["scheduler"] = job.stats()
            model._fit_metrics = metrics
            job._finish(model)
            reg.inc("scheduler.jobs_completed")
        except PreemptedError:
            requeue = True
        except BaseException as e:  # a dead tenant job must never leak its
            # reservation or wedge the queue — reclaim and keep scheduling
            job._fail(e)
            reg.inc("scheduler.jobs_failed")
            self._logger.warning(
                "job %d (tenant %r) failed: %s: %s",
                job.job_id, job.tenant, type(e).__name__, e,
            )
        finally:
            with self._lock:
                self._running.pop(job.job_id, None)
                if job._run_since is not None:
                    job.run_s += time.monotonic() - job._run_since
                    job._run_since = None
                self._ledger.release(job.reservation)
                job.reservation = None
                # the claim's chips return to the pool with the bytes; a
                # resumed job re-places first-fit — possibly on a DIFFERENT
                # equal-width run (checkpoints are chip-set agnostic:
                # host-side solver state re-placed at restore)
                job.chip_ids = None
                job.placed_devices = None
                if requeue and not self._closed:
                    job.preemptions += 1
                    job._preempt.clear()
                    job._preempt_reason = ""
                    job.state = "preempted"
                    job._wait_since = time.monotonic()
                    reg.inc("scheduler.jobs_preempted")
                    _audit.record_decision(
                        "preemption", "scheduler", "preempted",
                        subject=f"job:{job.job_id}", tenant=job.tenant,
                        preemptions=job.preemptions, priority=job.priority,
                    )
                    if (
                        job.preemptions >= self._max_preemptions
                        and job.stream_floor_estimate is not None
                        and not job.demote_to_stream
                    ):
                        # degraded-mode service: the chronically displaced
                        # job streams from here on — a floor-chunk footprint
                        # that packs into almost any budget
                        job.demote_to_stream = True
                        job.demoted = True
                        reg.inc("scheduler.jobs_demoted")
                        _audit.record_decision(
                            "demotion", "scheduler", "stream",
                            subject=f"job:{job.job_id}", tenant=job.tenant,
                            reason=(
                                f"preempted {job.preemptions} time(s) "
                                "(config['sched_max_preemptions'])"
                            ),
                        )
                        self._logger.warning(
                            "job %d (tenant %r) preempted %d time(s) — "
                            "demoting to the streaming path",
                            job.job_id, job.tenant, job.preemptions,
                        )
                    self._queue.append(job)
                elif requeue:
                    job._fail(RuntimeError("FitScheduler shut down mid-preemption"))
                self._schedule_locked()

    # ------------------------------------------------------------- stats --
    def stats(self) -> Dict[str, Any]:
        """Per-tenant roll-up of every job this scheduler has seen — queue
        waits (list + p50/p99 via the one shared quantile helper),
        preemptions, resumes, demotions, completion counts — plus the ledger
        view (reserved bytes, high watermark, utilization, per-tenant
        byte/chip-seconds) and the process-wide queue-wait percentiles
        (`telemetry.summarize_histogram` — the same extraction
        `ScoringEngine.stats` uses, so the two cannot drift)."""
        from .. import telemetry

        with self._lock:
            jobs = list(self._jobs)
            running = len(self._running)
            queued = len(self._queue)
        tenants: Dict[str, Dict[str, Any]] = {}
        for j in jobs:
            t = tenants.setdefault(
                j.tenant,
                {
                    "jobs": 0, "completed": 0, "failed": 0,
                    "preemptions": 0, "resumes": 0, "demotions": 0,
                    "queue_wait_s": [],
                },
            )
            t["jobs"] += 1
            t["completed"] += int(j.state == "completed")
            t["failed"] += int(j.state in ("failed", "refused"))
            t["preemptions"] += j.preemptions
            t["resumes"] += j.resumes
            t["demotions"] += int(j.demoted)
            t["queue_wait_s"].append(j.queue_wait_s)
        for t in tenants.values():
            t["queue_wait_p50_s"] = telemetry.quantile_of(t["queue_wait_s"], 0.5)
            t["queue_wait_p99_s"] = telemetry.quantile_of(t["queue_wait_s"], 0.99)
        wait = telemetry.summarize_histogram("scheduler.queue_wait_s")
        return {
            "tenants": tenants,
            "running": running,
            "queued": queued,
            "queue_wait_p50_s": wait["p50"],
            "queue_wait_p99_s": wait["p99"],
            "ledger_reserved_bytes": self._ledger.reserved_bytes(),
            "ledger_high_watermark": self._ledger.high_watermark,
            "ledger_utilization": self._ledger.utilization(),
            "ledger_occupied_chips": sorted(self._ledger.occupied_chips()),
            "chip_placement": self._chip_placement,
            "tenant_usage": self._ledger.tenant_usage(),
        }

    # ---------------------------------------------------------- shutdown --
    def shutdown(self, wait: bool = True, timeout: Optional[float] = None) -> None:
        """Stop admitting. Queued (never-run) jobs fail typed; running jobs
        finish (their threads are joined when `wait`). Idempotent."""
        with self._lock:
            self._closed = True
            drained, self._queue = self._queue, []
            threads = list(self._threads)
        for job in drained:
            job._fail(RuntimeError("FitScheduler shut down before the job ran"))
        if wait:
            deadline = None if timeout is None else time.monotonic() + timeout
            for t in threads:
                left = None if deadline is None else max(0.0, deadline - time.monotonic())
                t.join(left)

    def __enter__(self) -> "FitScheduler":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.shutdown(wait=True)
