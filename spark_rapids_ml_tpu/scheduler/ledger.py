#
# HbmLedger: ONE per-device byte ledger for every HBM consumer in the
# process (docs/scheduling.md "The shared ledger").
#
# Before this ledger, the two admission controllers each budgeted against the
# FULL device capacity: `memory.admit_fit` ignored bytes held by resident
# serving models, and `memory.admit_model_load` ignored a concurrently
# running fit's placement + workspace — so a fit plus resident models could
# jointly overshoot HBM even though each admission individually "fit". Both
# controllers now charge against capacity MINUS what this ledger already
# holds, and every admission RESERVES its estimate here:
#
#   kind "fit"    one reservation per fit, held from admission until the fit
#                 completes or fails (core releases it in the fit driver's
#                 finally); a scope-cached placement BETWEEN fits is pinned
#                 HBM but unreserved — the next fit over it re-reserves on
#                 the cache hit (documented gap: the idle window between
#                 fits in one device_dataset_scope is unaccounted).
#   kind "serve"  one reservation per resident serving model, held from
#                 admission through placement + prewarm + residency,
#                 released on eviction (serving.ModelRegistry).
#   kind "job"    one reservation per scheduler job, made by FitScheduler at
#                 queue admission and RESIZED (not duplicated) by the job's
#                 own `admit_fit` when the fit trues up the estimate;
#                 released when the job completes, fails, or is preempted.
#
# The ledger never decides anything — admission logic stays in `memory.py`
# (the ci/analysis `ledger-bypass` rule keeps capacity math there). It is
# bookkeeping with one atomicity guarantee: `admission()` is the lock every
# admission decision runs under, so check-then-reserve is race-free across
# concurrent fits, model loads, and scheduler passes.
#
from __future__ import annotations

import itertools
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..utils import lockcheck

__all__ = ["HbmReservation", "HbmLedger", "global_ledger", "reset_global_ledger"]


def _now() -> float:
    return time.monotonic()


def _fresh_usage() -> Dict[str, float]:
    return {"byte_seconds": 0.0, "chip_seconds": 0.0, "reservations": 0.0}


def _current_tenant() -> str:
    """The enclosing scheduler job's tenant, or "default" — so every HBM
    claim (standalone fits and serving loads included) lands in the
    per-tenant accounting without callers having to thread a tenant."""
    from . import context as _ctx

    job = _ctx.current_job()
    return str(job.tenant) if job is not None else "default"


@dataclass
class HbmReservation:
    """One admitted per-device byte claim. `nbytes` is mutable via
    `HbmLedger.resize` (a scheduler job's queue-time estimate is trued up by
    the fit's own admission); `active` flips False exactly once on release —
    double-release is a harmless no-op, never a double-credit.

    `tenant` and `chips` feed the per-tenant accounting (docs/observability.md
    "Ops plane"): the ledger integrates ``nbytes x seconds-held`` (HBM
    byte-seconds) and ``chips x seconds-held`` (chip-seconds) per tenant —
    `t0`/`mark` are the integration anchors (monotonic clock)."""

    owner: str
    kind: str  # "fit" | "serve" | "job"
    nbytes: int
    rid: int = 0
    active: bool = True
    tenant: str = "default"
    chips: int = 1
    t0: float = 0.0
    mark: float = 0.0  # last byte-seconds integration point


class HbmLedger:
    """Thread-safe reservation ledger (see module docstring).

    `admission_hooks` fire after every admission DECISION (admit or refuse)
    with ``(reserved_bytes, budget_bytes_or_None)`` — the test harness's
    "ledger never over capacity, asserted at every admission" hook, and the
    utilization gauge's feed."""

    def __init__(self) -> None:
        self._lock = lockcheck.make_lock("scheduler.ledger.HbmLedger._lock", "rlock")
        self._admission_lock = lockcheck.make_lock(
            "scheduler.ledger.HbmLedger._admission_lock", "rlock"
        )
        self._by_id: Dict[int, HbmReservation] = {}  # guarded-by: _lock
        self._ids = itertools.count(1)
        self.high_watermark: int = 0
        self.last_budget: Optional[int] = None
        self.admission_hooks: List[Callable[[int, Optional[int]], None]] = []
        # per-tenant integrated usage (byte-seconds / chip-seconds across
        # released AND resized claims; tenant_usage() adds the live ones)
        self._tenant_usage: Dict[str, Dict[str, float]] = {}  # guarded-by: _lock

    # ------------------------------------------------------------ locking --
    def admission(self):
        """The one lock every admission decision (check-then-reserve) runs
        under — `memory.admit_fit`, `memory.admit_model_load`, and the
        scheduler's co-admission pass all serialize here, so two concurrent
        admissions cannot both see the same free bytes."""
        return self._admission_lock

    # ------------------------------------------------------------- reads ---
    def reserved_bytes(
        self, *, kind: Optional[str] = None, exclude: Optional[HbmReservation] = None
    ) -> int:
        """Active reserved bytes, optionally one `kind` only, optionally
        excluding one reservation (an admission re-truing a job's own claim
        must not double-count itself)."""
        with self._lock:
            return sum(
                r.nbytes
                for r in self._by_id.values()
                if r.active
                and (kind is None or r.kind == kind)
                and r is not exclude
            )

    def reservations(self) -> List[HbmReservation]:
        with self._lock:
            return [r for r in self._by_id.values() if r.active]

    def utilization(self) -> Optional[float]:
        """reserved / last-known budget, or None while no budget was ever
        observed (CPU without an `hbm_budget_bytes` override)."""
        with self._lock:
            if not self.last_budget:
                return None
            return self.reserved_bytes() / float(self.last_budget)

    def tenant_usage(self) -> Dict[str, Dict[str, float]]:
        """Per-tenant integrated HBM accounting: `byte_seconds` (reserved
        bytes x wall seconds held) and `chip_seconds` (claimed chips x wall
        seconds), plus live claim state — the tenant cost view
        `ops_plane.report()` and `benchmark/opsreport.py` serve. Live
        reservations are integrated up to now. When the efficiency plane
        has attributed device time (ops_plane.efficiency), each tenant row
        additionally carries a `device_time` split so chip-seconds divide
        into execute/compile/host/idle."""
        now = _now()
        with self._lock:
            for r in self._by_id.values():
                if r.active:
                    self._accrue_locked(r, now)
            out: Dict[str, Dict[str, float]] = {}
            for tenant, u in self._tenant_usage.items():
                out[tenant] = dict(u)
            for r in self._by_id.values():
                if not r.active:
                    continue
                u = out.setdefault(r.tenant, _fresh_usage())
                u["live_bytes"] = u.get("live_bytes", 0.0) + r.nbytes
                u["live_reservations"] = u.get("live_reservations", 0.0) + 1
        # outside the ledger lock: the efficiency module has its own lock
        # (never import it from here — probe, so the accounting plane stays
        # optional and import-cycle-free)
        eff = sys.modules.get(
            (__package__ or "spark_rapids_ml_tpu.scheduler").rsplit(".", 1)[0]
            + ".ops_plane.efficiency"
        )
        if eff is not None:
            try:
                for tenant, split in eff.tenant_time_splits().items():
                    if tenant in out:
                        out[tenant]["device_time"] = split  # type: ignore[assignment]
                    else:
                        u = _fresh_usage()
                        u["device_time"] = split  # type: ignore[assignment]
                        out[tenant] = u
            except Exception:
                pass
        return out

    # ------------------------------------------------------------ writes ---
    def _accrue_locked(self, r: HbmReservation, now: float) -> None:
        """Integrate `r`'s byte/chip-seconds since its last mark (caller
        holds the lock; called at every nbytes change point and release, so
        each interval is charged at the bytes actually held through it)."""
        dt = max(0.0, now - r.mark)
        r.mark = now
        if dt == 0.0:
            return
        u = self._tenant_usage.setdefault(r.tenant, _fresh_usage())
        u["byte_seconds"] += r.nbytes * dt
        u["chip_seconds"] += r.chips * dt

    def reserve(
        self,
        owner: str,
        kind: str,
        nbytes: int,
        *,
        tenant: Optional[str] = None,
        chips: int = 1,
    ) -> HbmReservation:
        """Unconditional bookkeeping reserve — admission logic (memory.py)
        decides WHETHER; this records THAT. Updates the high watermark and
        the `scheduler.ledger_reserved_bytes` gauge. `tenant` defaults to
        the enclosing scheduler job's tenant (or "default") so standalone
        fits are accounted too."""
        if tenant is None:
            tenant = _current_tenant()
        now = _now()
        r = HbmReservation(
            owner=owner, kind=kind, nbytes=max(0, int(nbytes)),
            tenant=str(tenant), chips=max(1, int(chips)), t0=now, mark=now,
        )
        with self._lock:
            r.rid = next(self._ids)
            self._by_id[r.rid] = r
            u = self._tenant_usage.setdefault(r.tenant, _fresh_usage())
            u["reservations"] += 1
            self._note_locked()
        return r

    def try_reserve(
        self,
        owner: str,
        kind: str,
        nbytes: int,
        *,
        budget: Optional[int] = None,
        exclude: Optional[HbmReservation] = None,
        tenant: Optional[str] = None,
        chips: int = 1,
    ) -> Optional[HbmReservation]:
        """Atomic check-then-reserve: None when ``held + nbytes`` would
        exceed `budget` (a None budget always admits — no capacity
        information means no budgeting, the pre-ledger contract)."""
        with self._lock:
            if budget is not None:
                held = self.reserved_bytes(exclude=exclude)
                if held + max(0, int(nbytes)) > budget:
                    return None
            return self.reserve(owner, kind, nbytes, tenant=tenant, chips=chips)

    def resize(self, r: HbmReservation, nbytes: int) -> None:
        """True an existing claim up (or down) to `nbytes` — the scheduler
        job's queue-time estimate replaced by the fit admission's exact
        working set. The caller validated the new size against the budget
        (under `admission()`); resize itself is bookkeeping. The interval up
        to now is accounted at the OLD size (those were the bytes held)."""
        with self._lock:
            self._accrue_locked(r, _now())
            r.nbytes = max(0, int(nbytes))
            self._note_locked()

    def release(self, r: Optional[HbmReservation]) -> None:
        """Return a claim's bytes. Idempotent (a released reservation stays
        released); None is a no-op so callers can release unconditionally in
        `finally` blocks."""
        if r is None:
            return
        with self._lock:
            if not r.active:
                return
            self._accrue_locked(r, _now())
            r.active = False
            self._by_id.pop(r.rid, None)
            self._note_locked()

    # ---------------------------------------------------------- telemetry --
    def note_admission(self, budget: Optional[int]) -> None:
        """Record one admission DECISION against `budget`: remembers the
        budget (utilization denominator), publishes the
        `scheduler.ledger_utilization` gauge, and fires every admission hook
        — the acceptance harness asserts ``reserved <= budget`` here, at
        every admission, not just at the end."""
        from .. import telemetry

        with self._lock:
            if budget is not None:
                self.last_budget = int(budget)
            reserved = self.reserved_bytes()
            last = self.last_budget
        if telemetry.enabled() and last:
            telemetry.registry().gauge(
                "scheduler.ledger_utilization", reserved / float(last)
            )
        for hook in list(self.admission_hooks):
            hook(reserved, budget)

    def _note_locked(self) -> None:
        reserved = sum(r.nbytes for r in self._by_id.values() if r.active)
        if reserved > self.high_watermark:
            self.high_watermark = reserved
        from .. import telemetry

        if telemetry.enabled():
            telemetry.registry().gauge("scheduler.ledger_reserved_bytes", reserved)


# One ledger per process: fits, serving loads, and scheduler jobs all charge
# the same HBM, so they must share one book.
_GLOBAL = HbmLedger()
_GLOBAL_LOCK = lockcheck.make_lock("scheduler.ledger._GLOBAL_LOCK")


def global_ledger() -> HbmLedger:
    return _GLOBAL


def reset_global_ledger() -> HbmLedger:
    """Fresh process-global ledger (test isolation — a leaked reservation
    from a failed test must not shrink every later test's budget)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        _GLOBAL = HbmLedger()
    return _GLOBAL
