#
# HbmLedger: ONE per-device byte ledger for every HBM consumer in the
# process (docs/scheduling.md "The shared ledger").
#
# Before this ledger, the two admission controllers each budgeted against the
# FULL device capacity: `memory.admit_fit` ignored bytes held by resident
# serving models, and `memory.admit_model_load` ignored a concurrently
# running fit's placement + workspace — so a fit plus resident models could
# jointly overshoot HBM even though each admission individually "fit". Both
# controllers now charge against capacity MINUS what this ledger already
# holds, and every admission RESERVES its estimate here:
#
#   kind "fit"    one reservation per fit, held from admission until the fit
#                 completes or fails (core releases it in the fit driver's
#                 finally); a scope-cached placement BETWEEN fits is pinned
#                 HBM but unreserved — the next fit over it re-reserves on
#                 the cache hit (documented gap: the idle window between
#                 fits in one device_dataset_scope is unaccounted).
#   kind "serve"  one reservation per resident serving model, held from
#                 admission through placement + prewarm + residency,
#                 released on eviction (serving.ModelRegistry).
#   kind "job"    one reservation per scheduler job, made by FitScheduler at
#                 queue admission and RESIZED (not duplicated) by the job's
#                 own `admit_fit` when the fit trues up the estimate;
#                 released when the job completes, fails, or is preempted.
#
# The ledger never decides anything — admission logic stays in `memory.py`
# (the ci/analysis `ledger-bypass` rule keeps capacity math there). It is
# bookkeeping with one atomicity guarantee: `admission()` is the lock every
# admission decision runs under, so check-then-reserve is race-free across
# concurrent fits, model loads, and scheduler passes.
#
from __future__ import annotations

import itertools
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from ..utils import lockcheck

__all__ = [
    "HbmReservation",
    "HbmLedger",
    "global_ledger",
    "reset_global_ledger",
    "merge_tenant_usage",
]


def _now() -> float:
    return time.monotonic()


def _fresh_usage() -> Dict[str, float]:
    return {"byte_seconds": 0.0, "chip_seconds": 0.0, "reservations": 0.0}


def _current_tenant() -> str:
    """The enclosing scheduler job's tenant, or "default" — so every HBM
    claim (standalone fits and serving loads included) lands in the
    per-tenant accounting without callers having to thread a tenant."""
    from . import context as _ctx

    job = _ctx.current_job()
    return str(job.tenant) if job is not None else "default"


@dataclass
class HbmReservation:
    """One admitted per-device byte claim. `nbytes` is mutable via
    `HbmLedger.resize` (a scheduler job's queue-time estimate is trued up by
    the fit's own admission); `active` flips False exactly once on release —
    double-release is a harmless no-op, never a double-credit.

    `tenant` and `chips` feed the per-tenant accounting (docs/observability.md
    "Ops plane"): the ledger integrates ``nbytes x seconds-held`` (HBM
    byte-seconds) and ``chips x seconds-held`` (chip-seconds) per tenant —
    `t0`/`mark` are the integration anchors (monotonic clock).

    `chip_ids` is the PLACEMENT half of the 2-D book (docs/scheduling.md
    "2-D placement"): when set, the claim owns exactly those chips — byte
    budgeting applies per claimed chip, and occupancy is EXCLUSIVE (a second
    chip-scoped claim overlapping any of them is refused even with byte
    headroom, because two SPMD programs cannot time-share a chip without
    serializing). None keeps the 1-D contract: bytes span the whole pool,
    `chips` stays a pure accounting multiplier."""

    owner: str
    kind: str  # "fit" | "serve" | "job"
    nbytes: int
    rid: int = 0
    active: bool = True
    tenant: str = "default"
    chips: int = 1
    chip_ids: Optional[Tuple[int, ...]] = None
    t0: float = 0.0
    mark: float = 0.0  # last byte-seconds integration point


class HbmLedger:
    """Thread-safe reservation ledger (see module docstring).

    `admission_hooks` fire after every admission DECISION (admit or refuse)
    with ``(reserved_bytes, budget_bytes_or_None)`` — the test harness's
    "ledger never over capacity, asserted at every admission" hook, and the
    utilization gauge's feed."""

    def __init__(self) -> None:
        self._lock = lockcheck.make_lock("scheduler.ledger.HbmLedger._lock", "rlock")
        self._admission_lock = lockcheck.make_lock(
            "scheduler.ledger.HbmLedger._admission_lock", "rlock"
        )
        self._by_id: Dict[int, HbmReservation] = {}  # guarded-by: _lock
        self._ids = itertools.count(1)
        self.high_watermark: int = 0
        self.last_budget: Optional[int] = None
        # chip pool size for the occupancy half of the 2-D book (None until
        # a scheduler/test announces it via note_chip_pool) — the
        # denominator of chip-weighted utilization and the chips_idle gauge
        self.total_chips: Optional[int] = None
        self.admission_hooks: List[Callable[[int, Optional[int]], None]] = []
        # per-tenant integrated usage (byte-seconds / chip-seconds across
        # released AND resized claims; tenant_usage() adds the live ones)
        self._tenant_usage: Dict[str, Dict[str, float]] = {}  # guarded-by: _lock

    # ------------------------------------------------------------ locking --
    def admission(self):
        """The one lock every admission decision (check-then-reserve) runs
        under — `memory.admit_fit`, `memory.admit_model_load`, and the
        scheduler's co-admission pass all serialize here, so two concurrent
        admissions cannot both see the same free bytes."""
        return self._admission_lock

    # ------------------------------------------------------------- reads ---
    def reserved_bytes(
        self, *, kind: Optional[str] = None, exclude: Optional[HbmReservation] = None
    ) -> int:
        """Active reserved bytes, optionally one `kind` only, optionally
        excluding one reservation (an admission re-truing a job's own claim
        must not double-count itself)."""
        with self._lock:
            return sum(
                r.nbytes
                for r in self._by_id.values()
                if r.active
                and (kind is None or r.kind == kind)
                and r is not exclude
            )

    def reservations(self) -> List[HbmReservation]:
        with self._lock:
            return [r for r in self._by_id.values() if r.active]

    def reserved_bytes_on(
        self, chip: int, *, exclude: Optional[HbmReservation] = None
    ) -> int:
        """Active reserved bytes charged against ONE chip: chip-scoped
        claims count where they placed; legacy (chip_ids=None) claims span
        the whole pool, so they count on every chip — the conservative
        reading that keeps 1-D and 2-D claims honest against each other."""
        with self._lock:
            return sum(
                r.nbytes
                for r in self._by_id.values()
                if r.active
                and r is not exclude
                and (r.chip_ids is None or int(chip) in r.chip_ids)
            )

    def occupied_chips(
        self, *, exclude: Optional[HbmReservation] = None
    ) -> Set[int]:
        """Chip ids exclusively claimed by active chip-scoped reservations —
        the occupancy half of the 2-D book. Legacy claims (chip_ids=None)
        do not occupy: they budget bytes only, the pre-placement contract."""
        with self._lock:
            out: Set[int] = set()
            for r in self._by_id.values():
                if r.active and r is not exclude and r.chip_ids is not None:
                    out.update(r.chip_ids)
            return out

    def note_chip_pool(self, total_chips: Optional[int]) -> None:
        """Announce the chip pool size (scheduler passes do; tests may).
        Feeds chip-weighted utilization and the chips_idle gauge."""
        with self._lock:
            self.total_chips = None if total_chips is None else int(total_chips)

    def utilization(self) -> Optional[float]:
        """Reserved share of the budget, or None while no budget was ever
        observed (CPU without an `hbm_budget_bytes` override).

        With a known chip pool this is CHIP-WEIGHTED occupancy:
        ``sum(nbytes x chips) / (budget x total_chips)`` — a 4-chip fit on
        an 8-chip mesh reads as half the pool-bytes it actually holds, where
        the pre-2-D formula read it as whole-mesh utilization (the claim's
        bytes against one device's budget, chips ignored). Without a pool
        announcement the legacy per-device reading is kept."""
        with self._lock:
            if not self.last_budget:
                return None
            total = self.total_chips
            if total:
                weighted = sum(
                    r.nbytes
                    * (len(r.chip_ids) if r.chip_ids is not None else min(r.chips, total))
                    for r in self._by_id.values()
                    if r.active
                )
                return weighted / float(self.last_budget * total)
            return self.reserved_bytes() / float(self.last_budget)

    def tenant_usage(self) -> Dict[str, Dict[str, float]]:
        """Per-tenant integrated HBM accounting: `byte_seconds` (reserved
        bytes x wall seconds held) and `chip_seconds` (claimed chips x wall
        seconds), plus live claim state — the tenant cost view
        `ops_plane.report()` and `benchmark/opsreport.py` serve. Live
        reservations are integrated up to now. When the efficiency plane
        has attributed device time (ops_plane.efficiency), each tenant row
        additionally carries a `device_time` split so chip-seconds divide
        into execute/compile/host/idle."""
        now = _now()
        with self._lock:
            for r in self._by_id.values():
                if r.active:
                    self._accrue_locked(r, now)
            out: Dict[str, Dict[str, float]] = {}
            for tenant, u in self._tenant_usage.items():
                out[tenant] = dict(u)
            busy_union: Set[int] = set()  # chips exclusively claimed pool-wide
            legacy_span = 0  # widest chips multiplier among unplaced claims
            per_tenant_chips: Dict[str, Set[int]] = {}
            per_tenant_legacy: Dict[str, int] = {}
            for r in self._by_id.values():
                if not r.active:
                    continue
                u = out.setdefault(r.tenant, _fresh_usage())
                u["live_bytes"] = u.get("live_bytes", 0.0) + r.nbytes
                u["live_reservations"] = u.get("live_reservations", 0.0) + 1
                if r.chip_ids is not None:
                    busy_union.update(r.chip_ids)
                    per_tenant_chips.setdefault(r.tenant, set()).update(r.chip_ids)
                else:
                    legacy_span = max(legacy_span, r.chips)
                    per_tenant_legacy[r.tenant] = max(
                        per_tenant_legacy.get(r.tenant, 0), r.chips
                    )
            # chips_busy per tenant: the chips its placed claims own, or the
            # widest unplaced claim's span (unplaced claims share the pool,
            # so summing them would double count)
            for tenant, u in out.items():
                placed = per_tenant_chips.get(tenant)
                if placed is not None:
                    u["chips_busy"] = float(len(placed))
                elif tenant in per_tenant_legacy:
                    u["chips_busy"] = float(per_tenant_legacy[tenant])
            total = self.total_chips
            pool = out.setdefault("_pool", _fresh_usage())
            pool_busy = max(len(busy_union), legacy_span)
            pool["chips_busy"] = float(pool_busy)
            if total is not None:
                pool["chips_total"] = float(total)
                pool["chips_idle"] = float(max(0, total - pool_busy))
        # outside the ledger lock: the efficiency module has its own lock
        # (never import it from here — probe, so the accounting plane stays
        # optional and import-cycle-free)
        eff = sys.modules.get(
            (__package__ or "spark_rapids_ml_tpu.scheduler").rsplit(".", 1)[0]
            + ".ops_plane.efficiency"
        )
        if eff is not None:
            try:
                for tenant, split in eff.tenant_time_splits().items():
                    if tenant in out:
                        out[tenant]["device_time"] = split  # type: ignore[assignment]
                    else:
                        u = _fresh_usage()
                        u["device_time"] = split  # type: ignore[assignment]
                        out[tenant] = u
            except Exception:
                pass
        return out

    # ------------------------------------------------------------ writes ---
    def _accrue_locked(self, r: HbmReservation, now: float) -> None:
        """Integrate `r`'s byte/chip-seconds since its last mark (caller
        holds the lock; called at every nbytes change point and release, so
        each interval is charged at the bytes actually held through it)."""
        dt = max(0.0, now - r.mark)
        r.mark = now
        if dt == 0.0:
            return
        u = self._tenant_usage.setdefault(r.tenant, _fresh_usage())
        u["byte_seconds"] += r.nbytes * dt
        u["chip_seconds"] += r.chips * dt

    def reserve(
        self,
        owner: str,
        kind: str,
        nbytes: int,
        *,
        tenant: Optional[str] = None,
        chips: int = 1,
        chip_ids: Optional[Sequence[int]] = None,
    ) -> HbmReservation:
        """Unconditional bookkeeping reserve — admission logic (memory.py)
        decides WHETHER; this records THAT. Updates the high watermark and
        the `scheduler.ledger_reserved_bytes` gauge. `tenant` defaults to
        the enclosing scheduler job's tenant (or "default") so standalone
        fits are accounted too. A `chip_ids` claim places the reservation on
        exactly those chips (2-D book; `chips` follows the set's size)."""
        if tenant is None:
            tenant = _current_tenant()
        now = _now()
        placed = (
            None if chip_ids is None else tuple(sorted(int(c) for c in chip_ids))
        )
        if placed is not None:
            chips = len(placed)
        r = HbmReservation(
            owner=owner, kind=kind, nbytes=max(0, int(nbytes)),
            tenant=str(tenant), chips=max(1, int(chips)), chip_ids=placed,
            t0=now, mark=now,
        )
        with self._lock:
            r.rid = next(self._ids)
            self._by_id[r.rid] = r
            u = self._tenant_usage.setdefault(r.tenant, _fresh_usage())
            u["reservations"] += 1
            self._note_locked()
        return r

    def try_reserve(
        self,
        owner: str,
        kind: str,
        nbytes: int,
        *,
        budget: Optional[int] = None,
        exclude: Optional[HbmReservation] = None,
        tenant: Optional[str] = None,
        chips: int = 1,
        chip_ids: Optional[Sequence[int]] = None,
    ) -> Optional[HbmReservation]:
        """Atomic check-then-reserve: None when ``held + nbytes`` would
        exceed `budget` (a None budget always admits — no capacity
        information means no budgeting, the pre-ledger contract).

        With `chip_ids` the check is 2-D: occupancy first (any requested
        chip already exclusively claimed -> refused, even with byte
        headroom everywhere — chips don't time-share), then bytes PER
        CLAIMED CHIP (held-on-that-chip + nbytes against the per-device
        budget). Without `chip_ids` the legacy whole-pool byte check is
        kept — conservative against placed claims, which count on every
        chip they own and an unplaced claim spans them all."""
        with self._lock:
            if chip_ids is not None:
                want = {int(c) for c in chip_ids}
                if want & self.occupied_chips(exclude=exclude):
                    return None
                if budget is not None:
                    nb = max(0, int(nbytes))
                    for chip in want:
                        if self.reserved_bytes_on(chip, exclude=exclude) + nb > budget:
                            return None
            elif budget is not None:
                held = self.reserved_bytes(exclude=exclude)
                if held + max(0, int(nbytes)) > budget:
                    return None
            return self.reserve(
                owner, kind, nbytes,
                tenant=tenant, chips=chips, chip_ids=chip_ids,
            )

    def resize(self, r: HbmReservation, nbytes: int) -> None:
        """True an existing claim up (or down) to `nbytes` — the scheduler
        job's queue-time estimate replaced by the fit admission's exact
        working set. The caller validated the new size against the budget
        (under `admission()`); resize itself is bookkeeping. The interval up
        to now is accounted at the OLD size (those were the bytes held)."""
        with self._lock:
            self._accrue_locked(r, _now())
            r.nbytes = max(0, int(nbytes))
            self._note_locked()

    def rebind(
        self, r: HbmReservation, chip_ids: Optional[Sequence[int]]
    ) -> None:
        """Re-point a claim at a different chip set — the sub-mesh resize
        move (a recovered sweep re-meshing onto survivors, a resumed job
        landing on a different equal-width run). Like `resize`, bookkeeping
        only: the caller validated occupancy/bytes under `admission()`. The
        interval up to now accrues at the OLD width (those were the chips
        held)."""
        with self._lock:
            self._accrue_locked(r, _now())
            if chip_ids is None:
                r.chip_ids = None
            else:
                r.chip_ids = tuple(sorted(int(c) for c in chip_ids))
                r.chips = max(1, len(r.chip_ids))
            self._note_locked()

    def release(self, r: Optional[HbmReservation]) -> None:
        """Return a claim's bytes. Idempotent (a released reservation stays
        released); None is a no-op so callers can release unconditionally in
        `finally` blocks."""
        if r is None:
            return
        with self._lock:
            if not r.active:
                return
            self._accrue_locked(r, _now())
            r.active = False
            self._by_id.pop(r.rid, None)
            self._note_locked()

    # ---------------------------------------------------------- telemetry --
    def note_admission(self, budget: Optional[int]) -> None:
        """Record one admission DECISION against `budget`: remembers the
        budget (utilization denominator), publishes the
        `scheduler.ledger_utilization` gauge, and fires every admission hook
        — the acceptance harness asserts ``reserved <= budget`` here, at
        every admission, not just at the end."""
        from .. import telemetry

        with self._lock:
            if budget is not None:
                self.last_budget = int(budget)
            reserved = self.reserved_bytes()
            last = self.last_budget
            total = self.total_chips
            busy = len(self.occupied_chips())
            if busy == 0:
                busy = max(
                    (r.chips for r in self._by_id.values() if r.active),
                    default=0,
                )
                if total is not None:
                    busy = min(busy, total)
        if telemetry.enabled():
            if last:
                telemetry.registry().gauge(
                    "scheduler.ledger_utilization",
                    self.utilization() or 0.0,
                )
            telemetry.registry().gauge("scheduler.chips_busy", busy)
            if total is not None:
                telemetry.registry().gauge(
                    "scheduler.chips_idle", max(0, total - busy)
                )
        for hook in list(self.admission_hooks):
            hook(reserved, budget)

    def _note_locked(self) -> None:
        reserved = sum(r.nbytes for r in self._by_id.values() if r.active)
        if reserved > self.high_watermark:
            self.high_watermark = reserved
        from .. import telemetry

        if telemetry.enabled():
            telemetry.registry().gauge("scheduler.ledger_reserved_bytes", reserved)


# One ledger per process: fits, serving loads, and scheduler jobs all charge
# the same HBM, so they must share one book.
_GLOBAL = HbmLedger()
_GLOBAL_LOCK = lockcheck.make_lock("scheduler.ledger._GLOBAL_LOCK")


def global_ledger() -> HbmLedger:
    return _GLOBAL


def reset_global_ledger() -> HbmLedger:
    """Fresh process-global ledger (test isolation — a leaked reservation
    from a failed test must not shrink every later test's budget)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        _GLOBAL = HbmLedger()
    return _GLOBAL


def merge_tenant_usage(
    usages: Sequence[Dict[str, Dict[str, float]]]
) -> Dict[str, Dict[str, float]]:
    """Fleet rollup of per-host `tenant_usage()` maps (ops_plane.fleet,
    docs/observability.md "Fleet plane"): every numeric term sums across
    hosts — byte/chip-seconds, live bytes/reservations, chips_busy, the
    `_pool` pseudo-tenant's chips_total/chips_idle (each host owns disjoint
    chips, so occupancy adds), and the per-kind `device_time` splits. Hosts
    that never saw a tenant simply contribute nothing for it."""
    out: Dict[str, Dict[str, float]] = {}
    for usage in usages:
        for tenant, u in (usage or {}).items():
            acc = out.setdefault(str(tenant), {})
            for k, v in (u or {}).items():
                if k == "device_time" and isinstance(v, dict):
                    dt = acc.setdefault("device_time", {})  # type: ignore[assignment]
                    for kind, s in v.items():
                        if isinstance(s, (int, float)):
                            dt[kind] = dt.get(kind, 0.0) + float(s)
                elif isinstance(v, (int, float)):
                    acc[k] = acc.get(k, 0.0) + float(v)
    return out
