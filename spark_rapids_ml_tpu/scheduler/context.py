#
# Job context + the cooperative preemption flag (docs/scheduling.md
# "Preemption").
#
# A scheduler job's worker thread runs its whole fit inside `job_scope(job)`;
# everything downstream can then ask two questions without plumbing a job
# handle through every layer:
#
#   * `memory.admit_fit` asks `current_job()` — to RESIZE the job's ledger
#     reservation instead of double-reserving, and to honor a demoted job's
#     forced streaming verdict;
#   * the solvers ask `preemption_point(solver, iteration)` at their
#     checkpoint-cadence boundaries — the places they ALREADY host-fetch
#     (k-means' deferred-shift fetch, `run_segmented_while`'s segment
#     boundary, the streaming GLM loop), immediately AFTER the boundary's
#     `SolverCheckpoint` landed. A flagged job raises `PreemptedError` there
#     with ZERO lost work: the checkpoint it just saved is exactly what the
#     resume restores, so preempted-then-resumed is bit-identical to an
#     uninterrupted checkpointed fit (pinned by tests/test_scheduler.py).
#
# Context-local (same isolation argument as core's DeviceDataset scope and
# the checkpoint store): concurrent jobs on different worker threads must
# never see each other's flags. Outside any job both calls are near-free
# no-ops — one ContextVar read.
#
from __future__ import annotations

import contextlib
import contextvars
from typing import Any, Optional

__all__ = ["current_job", "job_scope", "preemption_point"]

_CURRENT_JOB: "contextvars.ContextVar[Optional[Any]]" = contextvars.ContextVar(
    "srml_scheduler_job", default=None
)


def current_job() -> Optional[Any]:
    """The `FitJob` whose worker thread is running this code, or None (the
    common, scheduler-less case)."""
    return _CURRENT_JOB.get()


@contextlib.contextmanager
def job_scope(job: Any):
    """Install `job` as the current job for the dynamic extent (the worker
    thread's whole fit attempt)."""
    token = _CURRENT_JOB.set(job)
    try:
        yield job
    finally:
        _CURRENT_JOB.reset(token)


def preemption_point(solver: str = "", iteration: int = 0) -> None:
    """Cooperative yield check — called by solvers at checkpoint-cadence
    boundaries, after the boundary checkpoint saved. Raises `PreemptedError`
    when the enclosing scheduler job has been asked to yield; a no-op (one
    ContextVar read) everywhere else."""
    job = _CURRENT_JOB.get()
    if job is not None:
        job.check_preempt(solver, iteration)
