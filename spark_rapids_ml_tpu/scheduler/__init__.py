#
# Multi-tenant fit scheduler (docs/scheduling.md): priority queues,
# bin-packed co-admission, and checkpoint preemption over one shared HBM
# ledger. Three parts:
#
#   ledger.py   `HbmLedger` — the ONE per-device byte book every HBM
#               consumer charges: fit admissions, serving model loads, and
#               scheduler jobs (fixes the split-brain where fits and
#               resident served models each budgeted against full capacity);
#   context.py  the job context + cooperative `preemption_point` the solvers
#               check at their checkpoint-cadence boundaries;
#   queue.py    `FitScheduler` / `FitJob` — submit(estimator, dataset,
#               tenant=, priority=) returning a future; co-admission,
#               preemption, resume, and streaming demotion.
#
from __future__ import annotations

from .context import current_job, job_scope, preemption_point  # noqa: F401
from .ledger import (  # noqa: F401
    HbmLedger,
    HbmReservation,
    global_ledger,
    reset_global_ledger,
)
from .queue import FitJob, FitScheduler  # noqa: F401

__all__ = [
    "HbmLedger",
    "HbmReservation",
    "global_ledger",
    "reset_global_ledger",
    "current_job",
    "job_scope",
    "preemption_point",
    "FitJob",
    "FitScheduler",
]
