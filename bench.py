#
# Round benchmark: the reference protocol's three headline fit configs
# (BASELINE.md — PCA k=3, KMeans k=1000 maxIter=30, LogisticRegression
# maxIter=200 reg=1e-5) at the TRUE protocol scale 1M x 3k, on the real TPU.
#
# Prints ONE JSON line on stdout:
#   {"metric", "value", "unit", "vs_baseline"}
# value = geometric mean of fit throughput (rows/sec/chip) across the three
# algos; per-algo detail goes to stderr. The full 10-config suite lives in
# benchmark/ (python -m benchmark.benchmark_runner protocol).
#
# RESILIENCE (reference parity: benchmark/databricks/run_benchmark.sh runs a
# time-limited, multi-attempt loop): the axon TPU tunnel flaps — it cost this
# repo the round-3 multichip artifact and the whole round-4 bench. So bench.py
# is a two-layer program:
#   * parent (this file, no args): retries the real bench as a subprocess with
#     bounded backoff; collects per-algo @RESULT lines from the child's stdout
#     as they complete, so a mid-run crash keeps finished algos and a retry
#     skips them. ALWAYS prints a parseable JSON line and exits 0 — a dead
#     tunnel yields {"value": 0.0, ...}, never a stack trace.
#   * child (--run): generates data and runs the algo sections, each fail-soft.
#
# Memory: X is 1M x 3000 f32 = 11.2 GiB, generated tile-wise DIRECTLY into a
# row-sharded HBM buffer (benchmark/gen_data.py) — peak = X + one 64k-row tile,
# inside a single v5e chip's 16 GB.
#
# Baseline normalization: the reference publishes a protocol + bar chart, no
# numbers (SURVEY.md §6). We normalize against A100-class per-algo assumptions
# on the 1M x 3k configs (2 workers): PCA 10 s, KMeans 60 s, LogReg 40 s
# => per-chip baselines {pca: 50k, kmeans: 8.3k, logreg: 12.5k} rows/sec/chip.
# vs_baseline = geomean(measured/baseline) — >1 beats the A100-class estimate.
#
from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Optional

import numpy as np

N_ROWS = int(os.environ.get("BENCH_ROWS", 1_000_000))
N_COLS = int(os.environ.get("BENCH_COLS", 3000))
# kmeans_scale / knn joined the headline geomean with the shared tiled
# distance core (docs/performance.md "Tiled distance core"): the r01->r03
# KMeans scaling cliff lived exactly in these lanes and the gate could not
# see it while they carried no baseline. A100-class per-algo assumptions on
# the same 1M x 3k shape (2 workers), like the original three:
#   kmeans_scale = ONE fused assignment+accumulate pass at k=1000
#     (~2 s/pass on A100-class: the 60 s / 30-iteration KMeans assumption)
#     => 1M / (2 s x 2 chips) = 250k rows/sec/chip;
#   knn = exact kNN of 4096 queries against the 1M items at k=64
#     (NearestNeighborsMG-class ~25 s on 2 workers)
#     => 1M / (25 s x 2 chips) = 20k rows/sec/chip (item-scan throughput).
# serving joined the headline geomean with the persistent serving plane
# (docs/serving.md): mixed-size concurrent predict requests against a
# resident k=1000 model at the protocol width, coalesced up the bucket
# ladder by the ScoringEngine. Baseline: the reference serves through a
# pandas_udf re-dispatched per query batch — Arrow serialization + Python
# re-entry per micro-batch caps an A100-class chip well below its one-pass
# assignment rate (250k rows/s); at the protocol's mixed 1-512 row request
# sizes we assume ~1/5 of it => 50k rows/sec/chip scored.
BASELINES = {
    "pca": 50_000.0,
    "kmeans": 8_333.0,
    "logreg": 12_500.0,
    "kmeans_scale": 250_000.0,
    "knn": 20_000.0,
    "serving": 50_000.0,
    # mixed-precision solver lanes (docs/performance.md "Mixed-precision
    # solvers"): the solver_precision="bf16" contract measured end-to-end.
    # Baselines reuse the f32 siblings' A100-reference rates — the reference
    # has no bf16 solver mode, so the speedup shows up as a higher vs_baseline
    # ratio on the same yardstick.
    "kmeans_bf16": 8_333.0,
    "logreg_bf16": 12_500.0,
}
# the serving lanes run FIRST: they build their own small resident models
# and must not coexist with the ~12 GiB dense protocol block on a single
# v5e. serving_saturation leads — it retunes the telemetry window buckets
# for its fast closed loop and resets the registry on exit, so running it
# before every other lane keeps their counters out of the blast radius.
ALGOS = (
    "serving_saturation", "serving", "pca", "logreg", "logreg_bf16",
    "kmeans", "kmeans_bf16", "kmeans_scale", "knn",
)
# lanes that run on ONE local device by construction (the serving plane's
# registry/engine are single-device): their rows/sec is already per-chip —
# dividing by the mesh size would underreport them n_chips-fold on
# multi-chip rounds and false-fail the lane gate vs single-chip history
SINGLE_DEVICE_LANES = {
    "serving", "serving_saturation", "sched_contention", "fleet_scale",
}
KNN_QUERIES = int(os.environ.get("BENCH_KNN_QUERIES", 4096))
KNN_K = int(os.environ.get("BENCH_KNN_K", 64))
SERVE_REQUESTS = int(os.environ.get("BENCH_SERVE_REQUESTS", 256))
SERVE_K = int(os.environ.get("BENCH_SERVE_K", 1000))
SERVE_CONCURRENCY = int(os.environ.get("BENCH_SERVE_CONCURRENCY", 8))

# Optional sparse lane (BENCH_SPARSE=1): the reference tests_large scale shape
# (1e7 x 2200 at 0.1% density) streamed partition-parallel from
# benchmark/gen_data_distributed.py into padded ELL — the full CSR is never
# materialized driver-side. Reported as its own @RESULT line; NOT part of the
# headline geomean (BASELINES has no entry for it).
SPARSE_ALGO = "sparse_logreg"
SPARSE_ROWS = int(os.environ.get("BENCH_SPARSE_ROWS", 10_000_000))
SPARSE_COLS = int(os.environ.get("BENCH_SPARSE_COLS", 2200))
SPARSE_DENSITY = float(os.environ.get("BENCH_SPARSE_DENSITY", 0.001))

# Optional CV grid-sweep lane (BENCH_CV=1): a numFolds x grid CrossValidator
# fit through the multi-fit engine (benchmark/bench_cv.py) — reports
# solves/sec and ingest-count-per-CV-fit (1 under the engine). Own @RESULT
# line; NOT part of the headline geomean (no BASELINES entry).
CV_ALGO = "cv_sweep"
CV_ROWS = int(os.environ.get("BENCH_CV_ROWS", 200_000))
CV_COLS = int(os.environ.get("BENCH_CV_COLS", 500))
CV_FOLDS = int(os.environ.get("BENCH_CV_FOLDS", 3))
CV_GRID = int(os.environ.get("BENCH_CV_GRID", 4))

# Optional out-of-core streaming lane (BENCH_OOCORE=1): the same dataset fit
# resident and demoted to the streaming path (benchmark/bench_oocore.py) —
# reports streaming rows/sec, the streaming/resident ratio, and the measured
# ingest.overlap_fraction (the double-buffer acceptance gauge). Own @RESULT
# line; NOT part of the headline geomean until the lane history stabilizes
# (no BASELINES entry).
OOCORE_ALGO = "oocore_stream"
OOCORE_ROWS = int(os.environ.get("BENCH_OOCORE_ROWS", 400_000))
OOCORE_COLS = int(os.environ.get("BENCH_OOCORE_COLS", 500))
OOCORE_CHUNK = int(os.environ.get("BENCH_OOCORE_CHUNK", 65_536))

# Optional multi-tenant scheduler contention lane (BENCH_SCHED=1): N tenants
# with adversarial job sizes through one FitScheduler over the shared HBM
# ledger (benchmark/bench_scheduler.py, docs/scheduling.md) — reports ledger
# utilization, per-tenant queue-wait p50/p99, and preemption counts. Own
# @RESULT line; NOT part of the headline geomean until the lane history
# stabilizes (no BASELINES entry).
SCHED_ALGO = "sched_contention"
SCHED_TENANTS = int(os.environ.get("BENCH_SCHED_TENANTS", 4))
SCHED_ROWS = int(os.environ.get("BENCH_SCHED_ROWS", 60_000))
SCHED_COLS = int(os.environ.get("BENCH_SCHED_COLS", 32))

# Co-admission utilization lane (rides BENCH_SCHED=1): the same two
# half-mesh fits co-admitted onto disjoint chip windows by the 2-D ledger
# vs time-sliced (benchmark/bench_scheduler.run_coadmission_bench,
# docs/scheduling.md "2-D placement") — reports the aggregate rows/sec
# ratio and the chip-occupancy integral of both phases. Own @RESULT line;
# NOT part of the headline geomean until the lane history stabilizes (no
# BASELINES entry — the PR-10 per-lane trajectory gate picks it up).
SCHED_COADMIT_ALGO = "sched_coadmit"
SCHED_COADMIT_ROWS = int(os.environ.get("BENCH_SCHED_COADMIT_ROWS", 40_000))

# Optional fleet observability lane (BENCH_FLEET=1): the multi-host scaling
# sweep on the CPU SPMD harness — N LocalRendezvous ranks streaming work
# through lockstep rounds WITH periodic fleet ops rounds riding the control
# plane (benchmark/bench_fleet.py, docs/observability.md "Fleet plane").
# Reports aggregate rows/sec at the widest rank count (`fleet_scale`), the
# per-count curve as `fleet_scale_<n>` sub-lanes, and pool utilization vs
# tenant count as `fleet_util`. Own @RESULT lines; NOT part of the headline
# geomean until the lane history stabilizes (no BASELINES entry — the PR-10
# per-lane trajectory gate picks each lane up at its first artifact).
FLEET_ALGO = "fleet_scale"
FLEET_RANKS = tuple(
    int(n) for n in os.environ.get("BENCH_FLEET_RANKS", "1,2,3").split(",") if n
)
FLEET_ROWS = int(os.environ.get("BENCH_FLEET_ROWS", 50_000))


def bench_algos() -> tuple:
    extra: tuple = ()
    if os.environ.get("BENCH_SPARSE"):
        # sparse FIRST: its ELL tensors are freed when its runner returns,
        # BEFORE the ~12 GiB dense protocol block is generated — running it
        # last would stack both datasets on the chip and OOM a single v5e
        extra += (SPARSE_ALGO,)
    if os.environ.get("BENCH_CV"):
        # CV lane also ahead of the dense block, for the same HBM reason
        extra += (CV_ALGO,)
    if os.environ.get("BENCH_OOCORE"):
        # streaming lane ahead of the dense block too: its resident baseline
        # fit is freed before the protocol X lands
        extra += (OOCORE_ALGO,)
    if os.environ.get("BENCH_SCHED"):
        # contention lane ahead of the dense block for the same HBM reason
        # (its per-tenant datasets are freed when the scheduler drains)
        extra += (SCHED_ALGO, SCHED_COADMIT_ALGO)
    if os.environ.get("BENCH_FLEET"):
        # fleet lane first: pure host-side harness (numpy + thread barriers),
        # no device state to collide with anything that follows
        extra = (FLEET_ALGO,) + extra
    return extra + ALGOS

# Parent retry policy (override for tests): attempts x per-attempt timeout,
# with a longer sleep after fast failures (backend-init class) than slow ones
# (mid-run fault: the tunnel is up, retry soon). READY_TIMEOUT bounds backend
# init SEPARATELY: a hung tunnel blocks inside jax backend init without ever
# erroring (the observed failure mode) — the child announces @READY once the
# mesh exists, and the parent kills inits that never get there instead of
# burning the whole attempt budget on one hang.
MAX_ATTEMPTS = int(os.environ.get("BENCH_ATTEMPTS", 10))
ATTEMPT_TIMEOUT_S = float(os.environ.get("BENCH_ATTEMPT_TIMEOUT", 2400))
READY_TIMEOUT_S = float(os.environ.get("BENCH_READY_TIMEOUT", 240))
# post-@READY progress budget: once the backend is live, @PHASE lane marks
# act as heartbeats — a lane silent past this is presumed deadlocked and
# killed without burning the whole attempt budget. Generous by design: the
# longest legitimately silent stretch is one lane's datagen + compile +
# timed fits (~several minutes at protocol scale through the tunnel).
PHASE_TIMEOUT_S = float(os.environ.get("BENCH_PHASE_TIMEOUT", 900))
BACKOFF_FAST_FAIL_S = float(os.environ.get("BENCH_BACKOFF", 60))
BACKOFF_SLOW_FAIL_S = 10.0
FAST_FAIL_WINDOW_S = 300.0  # died in <5 min => almost surely backend init


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


# ----------------------------------------------------------------- child ----


def _time_fit(run, fetch, repeats=2) -> float:
    """Wall-clock with forced device->host fetch (block_until_ready is not
    reliable on the experimental axon PJRT platform)."""
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = run()
        np.asarray(fetch(out))
        best = min(best, time.perf_counter() - t0)
    return best


def bench_pca(X, w, mesh) -> float:
    import jax

    from spark_rapids_ml_tpu.ops.pca import pca_fit, record_pca_fit

    fit = jax.jit(lambda X, w: pca_fit(X, w, k=3))
    state = fit(X, w)
    np.asarray(state["components_"])  # compile + warm
    fit_s = _time_fit(lambda: fit(X, w), lambda s: s["components_"])
    record_pca_fit(state, k=3)  # outside the timer
    _log(f"pca: {fit_s:.2f}s fit")
    return N_ROWS / fit_s


def bench_kmeans(X, w, mesh) -> float:
    import jax

    from spark_rapids_ml_tpu.ops.kmeans import kmeans_fit

    k = 1000
    # random-row init (initMode=random protocol config). The rows are iid BY
    # CONSTRUCTION (gen_classification_device draws every row from the same
    # mixture, in tile order independent of label), so ONE contiguous k-row
    # block at a random offset is an equally random sample. Do NOT point this
    # at ordered/clustered data (e.g. a parquet dataset sorted by label) —
    # there a contiguous block is a degenerate init; sample rows instead.
    # (Per-row pulls cost ~145 s of dispatch latency through the tunnel; a
    # fancy-index gather program on the 11 GiB X makes XLA materialize a full
    # copy — measured OOM.)
    rng = np.random.default_rng(1)
    r0 = int(rng.integers(0, max(1, X.shape[0] - k + 1)))
    centers0 = jax.jit(lambda X: jax.lax.dynamic_slice_in_dim(X, r0, k, 0))(X)
    np.asarray(centers0[:1])

    def run():
        from spark_rapids_ml_tpu.parallel.mesh import effective_matmul_precision

        # KMeans precision policy: 3-pass bf16 MXU (parallel/mesh.py dtype_scope)
        with jax.default_matmul_precision(effective_matmul_precision("BF16_BF16_F32_X3")):
            return kmeans_fit(
                X, w, centers0, mesh=mesh, max_iter=30, tol=1e-20, batch_rows=65536
            )

    np.asarray(run()["cluster_centers_"])  # compile + warm
    fit_s = _time_fit(run, lambda s: s["cluster_centers_"], repeats=1)
    _log(f"kmeans: {fit_s:.2f}s fit (k={k}, maxIter=30)")
    return N_ROWS / fit_s


def bench_kmeans_bf16(X, w, mesh) -> float:
    """The solver_precision="bf16" k-means lane, measured exactly as a user
    gets it: one-pass bf16-compute/f32-accumulate assignment + accumulation
    (distance-core fast path, autotuned block plan on TPU), final inertia at
    full precision — no ambient matmul-precision override. Distinct from the
    `kmeans` lane, which wraps its fit in the estimator's 3-pass-bf16
    dtype_scope policy."""
    import jax

    from spark_rapids_ml_tpu.ops.kmeans import kmeans_fit

    k = 1000
    rng = np.random.default_rng(1)  # same init block as the kmeans lane
    r0 = int(rng.integers(0, max(1, X.shape[0] - k + 1)))
    centers0 = jax.jit(lambda X: jax.lax.dynamic_slice_in_dim(X, r0, k, 0))(X)
    np.asarray(centers0[:1])

    def run():
        return kmeans_fit(
            X, w, centers0, mesh=mesh, max_iter=30, tol=1e-20,
            batch_rows=65536, precision_mode="fast",
        )

    np.asarray(run()["cluster_centers_"])  # compile + warm
    fit_s = _time_fit(run, lambda s: s["cluster_centers_"], repeats=1)
    _log(f"kmeans_bf16: {fit_s:.2f}s fit (k={k}, maxIter=30, solver_precision=bf16)")
    return N_ROWS / fit_s


def bench_kmeans_scale(X, w, mesh) -> float:
    """The distance-core lane: ONE fused assignment + accumulate pass over
    the full 1M x 3k block against k=1000 centers — the exact shape of the
    r01->r03 scaling cliff, now measured in isolation so the regression gate
    sees the tiled core's contribution separately from init/convergence."""
    import jax

    from spark_rapids_ml_tpu.ops.kmeans import kmeans_fit

    k = 1000
    rng = np.random.default_rng(7)
    r0 = int(rng.integers(0, max(1, X.shape[0] - k + 1)))
    centers0 = jax.jit(lambda X: jax.lax.dynamic_slice_in_dim(X, r0, k, 0))(X)
    np.asarray(centers0[:1])

    def run():
        from spark_rapids_ml_tpu.parallel.mesh import effective_matmul_precision

        with jax.default_matmul_precision(effective_matmul_precision("BF16_BF16_F32_X3")):
            # max_iter=1, no final inertia pass: one assignment+accumulate
            # sweep + the center update, nothing else
            return kmeans_fit(
                X, w, centers0, mesh=mesh, max_iter=1, tol=1e-20,
                batch_rows=65536, final_inertia=False,
            )

    np.asarray(run()["cluster_centers_"])  # compile + warm
    fit_s = _time_fit(run, lambda s: s["cluster_centers_"], repeats=2)
    _log(f"kmeans_scale: {fit_s:.2f}s one-pass assignment (k={k})")
    return N_ROWS / fit_s


def bench_knn(X, w, mesh) -> float:
    """Exact kNN lane: 4096 replicated queries against the row-sharded 1M
    items at k=64 — the NearestNeighborsMG workload on the shared tiled
    top-k core. Reported as item-scan throughput (items / second / chip),
    the same normalization as the fit lanes."""
    import jax

    from spark_rapids_ml_tpu.ops.knn import exact_knn

    Q = jax.jit(lambda X: jax.lax.dynamic_slice_in_dim(X, 0, KNN_QUERIES, 0))(X)
    np.asarray(Q[:1])

    def run():
        return exact_knn(X, w > 0, Q, mesh=mesh, k=KNN_K)

    np.asarray(run()[0])  # compile + warm
    search_s = _time_fit(run, lambda out: out[0], repeats=2)
    _log(f"knn: {search_s:.2f}s kneighbors ({KNN_QUERIES} queries, k={KNN_K})")
    return N_ROWS / search_s


def bench_logreg(X, w, y_idx) -> float:
    from spark_rapids_ml_tpu import telemetry
    from spark_rapids_ml_tpu.ops.logistic import logistic_fit

    run = lambda: logistic_fit(  # noqa: E731
        X, y_idx, w, k=2, multinomial=False, lam_l2=1e-5,
        fit_intercept=True, standardize=True, max_iter=200, tol=1e-30,
    )
    state = run()
    np.asarray(state["coef_"])  # compile + warm
    fit_s = _time_fit(lambda: run(), lambda s: s["coef_"], repeats=1)
    telemetry.record_solver_result(  # outside the timer
        "logistic", n_iter=int(state["n_iter_"]), objective=float(state["objective_"])
    )
    _log(f"logreg: {fit_s:.2f}s fit (maxIter=200, tol=1e-30)")
    return N_ROWS / fit_s


def bench_logreg_bf16(X, w, y_idx) -> float:
    """The solver_precision="bf16" GLM lane: X·β / Xᵀr matvecs bf16-in with
    f32 accumulation (ops/logistic._dense_ops), L-BFGS state + line search +
    convergence scalars full precision — same protocol config as `logreg`."""
    from spark_rapids_ml_tpu import telemetry
    from spark_rapids_ml_tpu.ops.logistic import logistic_fit

    run = lambda: logistic_fit(  # noqa: E731
        X, y_idx, w, k=2, multinomial=False, lam_l2=1e-5,
        fit_intercept=True, standardize=True, max_iter=200, tol=1e-30,
        fast=True,
    )
    state = run()
    np.asarray(state["coef_"])  # compile + warm
    fit_s = _time_fit(lambda: run(), lambda s: s["coef_"], repeats=1)
    telemetry.record_solver_result(  # outside the timer
        "logistic", n_iter=int(state["n_iter_"]), objective=float(state["objective_"])
    )
    _log(f"logreg_bf16: {fit_s:.2f}s fit (maxIter=200, solver_precision=bf16)")
    return N_ROWS / fit_s


def bench_sparse_logreg(mesh) -> float:
    """Sparse scale-shape fit: stream gen_data_distributed partitions into
    ELL (chunked, no full-CSR materialization), binarize the target, fit the
    certified tests_large config (scale-only standardization, maxIter=60)."""
    from benchmark.gen_data_distributed import sparse_classification_ell
    from spark_rapids_ml_tpu.ops.logistic import logistic_fit_ell

    t0 = time.perf_counter()
    data = sparse_classification_ell(SPARSE_ROWS, SPARSE_COLS, SPARSE_DENSITY, 0, mesh)
    np.asarray(data["w"][:1])
    _log(f"sparse datagen+ingest: {time.perf_counter() - t0:.1f}s (k_max={data['k_max']})")

    run = lambda: logistic_fit_ell(  # noqa: E731
        data["values"], data["indices"], data["y"], data["w"],
        d=SPARSE_COLS, k=2, multinomial=False, lam_l2=1e-6,
        fit_intercept=True, standardize=True, max_iter=60, tol=1e-12,
    )
    state = run()
    np.asarray(state["coef_"])  # compile + warm
    fit_s = _time_fit(run, lambda s: s["coef_"], repeats=1)
    from spark_rapids_ml_tpu import telemetry

    telemetry.record_solver_result(  # outside the timer
        "sparse_logistic", n_iter=int(state["n_iter_"]), objective=float(state["objective_"])
    )
    _log(f"sparse_logreg: {fit_s:.2f}s fit ({SPARSE_ROWS}x{SPARSE_COLS} @ {SPARSE_DENSITY})")
    return SPARSE_ROWS / fit_s


def bench_cv_lane() -> float:
    """CrossValidator grid sweep through the multi-fit engine: one ingest +
    one layout for numFolds x grid solves (+ the refit). Reports rows
    processed across all solves per second; the engine counters go to
    stderr and ride the @TELEMETRY snapshot."""
    from benchmark.bench_cv import run_cv_fit

    out = run_cv_fit(CV_ROWS, CV_COLS, num_folds=CV_FOLDS, grid_size=CV_GRID)
    _log(
        f"cv_sweep: {out['fit']:.2f}s for {int(out['solves'])} solves "
        f"({out['solves_per_sec']:.2f} solves/s, {int(out['ingests'])} ingest(s), "
        f"{int(out['solves_batched'])} batched / "
        f"{int(out['solves_sequential'])} sequential)"
    )
    return out["solves"] * CV_ROWS / out["fit"]


def bench_oocore_lane() -> float:
    """Streaming-vs-resident fit over one host dataset: reports streaming
    rows/sec (the lane metric), the throughput ratio, the double-buffer
    overlap fraction, and the live parity delta (~1e-9). Counters ride the
    @TELEMETRY snapshot."""
    from benchmark.bench_oocore import run_oocore_fit

    out = run_oocore_fit(OOCORE_ROWS, OOCORE_COLS, chunk_rows=OOCORE_CHUNK)
    _log(
        f"oocore_stream: {out['stream_s']:.2f}s streamed vs "
        f"{out['resident_s']:.2f}s resident "
        f"(ratio {out['stream_vs_resident']:.2f}, "
        f"overlap {out['overlap_fraction']:.2f} over "
        f"{int(out['stream_chunks'])} chunks, "
        f"max_rel_diff {out['max_rel_diff']:.2e})"
    )
    return out["stream_rows_per_sec"]


def bench_scheduler_lane() -> float:
    """Multi-tenant contention lane (docs/scheduling.md): N tenants with
    adversarial sizes through one FitScheduler over the shared HBM ledger.
    Reports ledger utilization, per-tenant queue-wait p50/p99, and
    preemption/demotion counts; over-budget admissions are a correctness
    failure, not a slow lane. The lane metric is total fit rows/sec."""
    from benchmark.bench_scheduler import run_scheduler_bench

    out = run_scheduler_bench(SCHED_TENANTS, SCHED_ROWS, SCHED_COLS)
    _log(
        f"sched_contention: {out['wall_s']:.2f}s for {int(out['jobs'])} jobs "
        f"({out['rows_per_sec']:,.0f} rows/s, utilization "
        f"{out['utilization']:.2f}, queue-wait p50 {out['queue_wait_p50_s']*1e3:.1f}ms "
        f"/ p99 {out['queue_wait_p99_s']*1e3:.1f}ms, "
        f"{int(out['preemptions'])} preemption(s), "
        f"{int(out['demotions'])} demotion(s))"
    )
    if out["ledger_over_budget_admissions"]:
        raise RuntimeError(
            "sched_contention lane: ledger exceeded the budget at "
            f"{int(out['ledger_over_budget_admissions'])} admission(s)"
        )
    # report-only ops embed (SLO verdict + per-tenant byte-seconds): rides
    # the BENCH record's "ops" key, never the gated geomean
    return out["rows_per_sec"], None, {
        "slo": out.get("slo", {}),
        "tenant_byte_seconds": out.get("tenant_byte_seconds", {}),
    }


def bench_sched_coadmit_lane() -> tuple:
    """Co-admission utilization lane (docs/scheduling.md "2-D placement"):
    two half-mesh fits co-admitted onto disjoint chip windows vs the same
    fits time-sliced. The lane metric is concurrent aggregate fit rows/sec;
    the rows/sec ratio and the chip-occupancy integrals ride the record's
    report-only `ops` embed. Cross-placement result divergence is a
    correctness failure, not a slow lane."""
    from benchmark.bench_scheduler import run_coadmission_bench

    out = run_coadmission_bench(SCHED_COADMIT_ROWS, SCHED_COLS)
    _log(
        f"sched_coadmit: {out['wall_concurrent_s']:.2f}s concurrent vs "
        f"{out['wall_sliced_s']:.2f}s time-sliced "
        f"(rows/s ratio {out['rows_per_sec_ratio']:.2f}, occupancy "
        f"{out['avg_chips_concurrent']:.1f} vs {out['avg_chips_sliced']:.1f} "
        f"avg chips of {int(out['pool_chips'])}, "
        f"max_abs_diff {out['max_abs_diff']:.1e})"
    )
    if out["max_abs_diff"] != 0.0:
        raise RuntimeError(
            "sched_coadmit lane: co-admitted results differ from time-sliced "
            f"(max_abs_diff={out['max_abs_diff']})"
        )
    return out["rows_per_sec_concurrent"], None, {
        "rows_per_sec_ratio": round(out["rows_per_sec_ratio"], 3),
        "rows_per_sec_sliced": round(out["rows_per_sec_sliced"], 1),
        "occupancy": {
            "pool_chips": out["pool_chips"],
            "avg_chips_concurrent": round(out["avg_chips_concurrent"], 2),
            "avg_chips_sliced": round(out["avg_chips_sliced"], 2),
            "peak_chips_concurrent": out["peak_chips_concurrent"],
            "peak_chips_sliced": out["peak_chips_sliced"],
            "chip_seconds_concurrent": round(out["chip_seconds_concurrent"], 3),
            "chip_seconds_sliced": round(out["chip_seconds_sliced"], 3),
            "ratio": round(out["occupancy_ratio"], 3),
        },
    }


def bench_fleet_lane() -> tuple:
    """Fleet observability lane (docs/observability.md "Fleet plane"): the
    multi-host scaling sweep — aggregate rows/sec with the piggybacked ops
    rounds riding the control plane — plus the utilization-vs-tenants sweep
    over the 2-D ledger rollup. The lane metric is rows/sec at the widest
    rank count; each rank count's value and the utilization number ride
    their own @RESULT lanes so the per-lane trajectory gate sees the curve.
    A failed ops round here is a correctness failure, not a slow lane: the
    plane's whole contract is that aggregation never breaks the fit."""
    from benchmark.bench_fleet import (
        run_fleet_scaling_bench,
        run_fleet_utilization_bench,
    )

    out = run_fleet_scaling_bench(FLEET_RANKS, FLEET_ROWS)
    util = run_fleet_utilization_bench()
    _log(
        f"fleet_scale: {out['rows_per_sec']:,.0f} rows/s aggregate at "
        f"{int(out['nranks'])} ranks (curve "
        + ", ".join(f"n={k}: {v:,.0f}" for k, v in out["scale"].items())
        + f"), {int(out['ops_rounds'])} ops round(s), "
        f"{int(out['ops_rounds_failed'])} failed; utilization "
        f"{util['utilization']:.2f} at {int(util['tenants'])} tenants over "
        f"{int(util['pool_chips'])} chips"
    )
    if out["ops_rounds_failed"]:
        raise RuntimeError(
            f"fleet_scale lane: {int(out['ops_rounds_failed'])} ops round(s) "
            "failed on a healthy harness"
        )
    # per-count scaling curve + pool utilization: own higher-better
    # trajectory lanes (no BASELINES entries — never in the geomean)
    for n, v in out["scale"].items():
        print(
            "@RESULT " + json.dumps(
                {"algo": f"fleet_scale_{n}", "rows_per_sec_chip": v}
            ),
            flush=True,
        )
    print(
        "@RESULT " + json.dumps(
            {"algo": "fleet_util", "rows_per_sec_chip": util["utilization"]}
        ),
        flush=True,
    )
    return out["rows_per_sec"], None, {
        "ops_rounds": out["ops_rounds"],
        "ranks_reporting": out.get("ranks_reporting"),
        "cluster_healthy": out.get("cluster_healthy"),
        "utilization": util["sweep"],
    }


def bench_serving_lane() -> tuple:
    """Serving-plane lane (docs/serving.md): mixed-size concurrent predict
    requests against a resident k=SERVE_K model at the protocol width through
    the ScoringEngine (admission + ladder prewarm + coalescing). Returns
    (rows scored per second, {p50/p99 latency ms}) — the latency dict rides
    the BENCH record's `latency_lanes` embed, which benchmark/regression.py
    gates as LOWER-IS-BETTER lanes (a p99 blowup fails even when throughput
    hides it)."""
    from benchmark.bench_serving import run_serving_bench

    out = run_serving_bench(
        n_cols=N_COLS, k=SERVE_K,
        n_requests=SERVE_REQUESTS, concurrency=SERVE_CONCURRENCY,
    )
    _log(
        f"serving: {out['qps']:.1f} qps, p50 {out['p50_ms']:.2f}ms / "
        f"p99 {out['p99_ms']:.2f}ms, {out['rows_per_sec']:,.0f} rows/s "
        f"({int(out['coalesced_batches'])}/{int(out['batches'])} batches "
        f"coalesced, {int(out['prewarmed_programs'])} rungs prewarmed, "
        f"max_abs_diff {out['max_abs_diff']:.1e})"
    )
    if out["max_abs_diff"] != 0.0:
        # coalesced != solo is a correctness failure, not a slow lane
        raise RuntimeError(
            f"serving lane: coalesced responses differ from solo predicts "
            f"(max_abs_diff={out['max_abs_diff']})"
        )
    # QPS rides the record's "lanes" as its own higher-better trajectory
    # lane (no BASELINES entry — not in the geomean; rows/sec is the
    # headline serving value, QPS the request-rate view of the same run)
    print(
        "@RESULT " + json.dumps({"algo": "serving_qps", "rows_per_sec_chip": out["qps"]}),
        flush=True,
    )
    return out["rows_per_sec"], {
        "serving_p50_ms": round(out["p50_ms"], 3),
        "serving_p99_ms": round(out["p99_ms"], 3),
    }, {
        # report-only ops embed, same contract as the scheduler lane's
        "slo": out.get("slo", {}),
        "tenant_byte_seconds": out.get("tenant_byte_seconds", {}),
    }


def bench_saturation_lane() -> tuple:
    """Serving saturation lane (docs/serving.md "Overload & backpressure"):
    a chaos `burst:stage=serve` plan ramps offered load past the measured
    plateau and the closed loop — deadline admission, bounded queue, the
    per-tenant backpressure ladder, adaptive batching — must degrade
    gracefully. The runner's hard gates (zero over-deadline dispatches,
    deadline-bounded served p99, goodput within a factor of the plateau,
    every ladder transition audited) raise here, so a graceful-overload
    regression is a FAILED lane, not a slower number. Lane value: rows/sec
    of goodput sustained UNDER the burst; the served p99 rides the record's
    `latency_lanes` embed (lower-is-better gate)."""
    from benchmark.bench_saturation import run_saturation_bench

    out = run_saturation_bench()
    _log(
        f"serving_saturation: plateau {out['plateau_rows_per_sec']:,.0f} rows/s, "
        f"burst offered {out['burst_offered_rows_per_sec']:,.0f} -> served "
        f"{out['burst_rows_per_sec']:,.0f} rows/s (p99 {out['burst_p99_ms']:.0f}ms, "
        f"deadline {out['deadline_ms']:.0f}ms), recovered to "
        f"{out['recover_rows_per_sec']:,.0f} rows/s at level "
        f"{out['final_level']!r} in {out['recover_wait_s']:.1f}s; "
        f"{int(out['shed_requests'])} shed / {int(out['throttled_requests'])} "
        f"throttled / {int(out['rejected_requests'])} rejected / "
        f"{int(out['expired_requests'])} expired, {int(out['transitions'])} "
        f"audited transition(s) [{', '.join(out['audited_verdicts'])}]"
    )
    failed = [n for n, g in out["gates"].items() if not g["ok"]]
    if failed:
        raise RuntimeError(
            "serving_saturation gates failed: "
            + "; ".join(f"{n}: {out['gates'][n]['detail']}" for n in failed)
        )
    return out["burst_rows_per_sec"], {
        "saturation_p99_ms": round(out["burst_p99_ms"], 3),
    }, {
        # report-only ops embed: the gate verdicts + ladder evidence
        "gates": {n: g["ok"] for n, g in out["gates"].items()},
        "audited_verdicts": out["audited_verdicts"],
        "transitions": out["transitions"],
    }


def _phase(name: str) -> None:
    """Structured heartbeat to the parent watchdog: `@PHASE <name>` on stdout.
    Any phase line counts as PROGRESS — the parent only kills a child whose
    LAST phase went silent past the budget, so it can tell a hung backend
    init (stuck at `backend-init`) from a slow compile (progressing through
    `lane:*` phases). The phase history rides the BENCH JSON (`attempts`)."""
    print(f"@PHASE {name}", flush=True)


def run_child() -> int:
    """Generate data once, run each pending algo fail-soft, emit @RESULT lines."""
    _phase("backend-init")  # first breath: the parent now knows we launched
    import jax

    from benchmark.gen_data import gen_classification_device
    from spark_rapids_ml_tpu import telemetry
    from spark_rapids_ml_tpu.parallel import get_mesh

    skip = set(filter(None, os.environ.get("BENCH_SKIP", "").split(",")))
    pending = [a for a in bench_algos() if a not in skip]
    if not pending:
        return 0

    # Registry telemetry (counters/gauges/span aggregates) is host-side and
    # cheap — enable it so the BENCH emission carries the per-stage snapshot.
    # Per-iteration convergence tracing stays OFF unless the env asks: a host
    # callback per solver iteration is a dispatch round-trip through the
    # tunnel and would poison the timings.
    telemetry.enable()

    mesh = get_mesh()
    print("@READY", flush=True)  # backend init survived — parent relaxes its watchdog
    n_chips = int(mesh.devices.size)

    dense: dict = {}

    def dense_data() -> dict:
        """Generate the dense protocol block LAZILY, on the first dense
        runner — so the sparse lane (which runs first) never coexists with
        the ~12 GiB dense X on the chip."""
        if not dense:
            _phase("warmup")  # datagen + first-compile: slow but PROGRESSING
            t0 = time.perf_counter()
            _log(f"generating {N_ROWS}x{N_COLS} dataset tile-wise ON DEVICE...")
            # single chip: plain (uncommitted-sharding) arrays — a committed
            # NamedSharding makes Shardy insert a full input-resharding copy of
            # X in downstream programs (11 GiB here), while GSPMD on a 1-device
            # mesh needs no sharding annotations at all
            X, y_idx, w = gen_classification_device(
                N_ROWS, N_COLS, n_classes=2, mesh=mesh if n_chips > 1 else None
            )
            np.asarray(w[:1])  # force materialization for honest phase timing
            _log(f"datagen: {time.perf_counter() - t0:.1f}s")
            dense.update(X=X, y_idx=y_idx, w=w)
        return dense

    runners = {
        SPARSE_ALGO: lambda: bench_sparse_logreg(mesh),
        CV_ALGO: lambda: bench_cv_lane(),
        OOCORE_ALGO: lambda: bench_oocore_lane(),
        SCHED_ALGO: lambda: bench_scheduler_lane(),
        SCHED_COADMIT_ALGO: lambda: bench_sched_coadmit_lane(),
        FLEET_ALGO: lambda: bench_fleet_lane(),
        "serving_saturation": lambda: bench_saturation_lane(),
        "serving": lambda: bench_serving_lane(),
        "pca": lambda: bench_pca(dense_data()["X"], dense_data()["w"], mesh),
        "logreg": lambda: bench_logreg(
            dense_data()["X"], dense_data()["w"], dense_data()["y_idx"]
        ),
        "logreg_bf16": lambda: bench_logreg_bf16(
            dense_data()["X"], dense_data()["w"], dense_data()["y_idx"]
        ),
        "kmeans": lambda: bench_kmeans(dense_data()["X"], dense_data()["w"], mesh),
        "kmeans_bf16": lambda: bench_kmeans_bf16(
            dense_data()["X"], dense_data()["w"], mesh
        ),
        "kmeans_scale": lambda: bench_kmeans_scale(
            dense_data()["X"], dense_data()["w"], mesh
        ),
        "knn": lambda: bench_knn(dense_data()["X"], dense_data()["w"], mesh),
    }
    from spark_rapids_ml_tpu.ops_plane import efficiency as _eff

    def _eff_totals() -> dict:
        # process-cumulative attribution totals (all tenants) + the compile
        # ledger — per-lane deltas of these ride the BENCH record
        tot = {"execute_s": 0.0, "compile_s": 0.0, "host_s": 0.0, "idle_s": 0.0}
        for split in _eff.tenant_time_splits().values():
            for k in tot:
                tot[k] += float(split.get(k, 0.0))
        comp = _eff.compile_stats()
        tot["compile_misses"] = float(comp["misses"])
        tot["compile_hits"] = float(comp["hits"])
        tot["compile_wall_s"] = float(comp["wall_s"])
        return tot

    n_fail = 0
    for name in pending:
        _phase(f"lane:{name}:start")
        try:
            eff_before = _eff_totals()
            out = runners[name]()
            # a lane may return (value, latency_dict[, ops_dict]): latency
            # values ride the @RESULT line into the BENCH record's
            # `latency_lanes` embed; the ops dict (SLO verdict + per-tenant
            # byte-seconds) rides report-only under `ops`
            latency = ops = None
            if isinstance(out, tuple):
                out, latency, ops = (out + (None,))[:3]
            v = out if name in SINGLE_DEVICE_LANES else out / n_chips
            rec = {"algo": name, "rows_per_sec_chip": v}
            if latency:
                rec["latency"] = latency
            if ops:
                rec["ops"] = ops
            # the lane's efficiency delta (execute/compile/host/idle split
            # plus compile-ledger movement), report-only under `ops` —
            # regression.py never reads it. MFU rides along when a peak
            # spec is configured (last attributed scope's gauge).
            eff_after = _eff_totals()
            eff_delta = {k: eff_after[k] - eff_before[k] for k in eff_after}
            if any(v_ > 0 for v_ in eff_delta.values()):
                if _eff.peak_flops() is not None:
                    gauges = telemetry.snapshot().get("gauges", {})
                    for g in ("efficiency.mfu", "efficiency.serve_mfu"):
                        if g in gauges:
                            eff_delta[g.split(".", 1)[1]] = gauges[g]
                rec.setdefault("ops", {})["efficiency"] = eff_delta
            print("@RESULT " + json.dumps(rec), flush=True)
            _phase(f"lane:{name}:end")
        except Exception as e:  # fail-soft: one dead section keeps the rest
            n_fail += 1
            _phase(f"lane:{name}:failed")
            _log(f"bench[{name}] FAILED: {type(e).__name__}: {e}")
    # per-stage telemetry snapshot (HBM watermark, solver iterations, span
    # aggregates) for the parent to embed in the BENCH JSON line
    telemetry.record_device_memory()
    snap = telemetry.snapshot()
    # precision provenance: which distance kernel actually ran, the session's
    # solver_precision default, and the autotuner's hit/miss/measure counts —
    # embedded so every BENCH record is interpretable without the stderr log
    from spark_rapids_ml_tpu.core import config as _srml_config
    from spark_rapids_ml_tpu.ops import autotune as _autotune
    from spark_rapids_ml_tpu.ops.distance import kernel_mode as _kernel_mode

    snap["precision"] = {
        "distance_kernel_mode": _kernel_mode(),
        "solver_precision": _srml_config["solver_precision"],
        "autotune": _autotune.stats(),
    }
    print("@TELEMETRY " + json.dumps(snap), flush=True)
    return 1 if n_fail else 0


# ---------------------------------------------------------------- parent ----


def _run_child_watched(env: dict, attempt_timeout: float):
    """Run one bench child with a PROGRESS watchdog plus a hard deadline.

    The child must emit a structured progress line (`@PHASE`, `@READY`, or
    `@RESULT`) at least every READY_TIMEOUT_S before the backend is up and
    every PHASE_TIMEOUT_S after — a hung backend init goes silent at
    `backend-init` and dies on the short budget; a lane that deadlocks
    post-init dies on the long one instead of burning the whole attempt; and
    the kill reason names the exact phase that stalled instead of the old
    blind "> 240s to @READY" with zero visibility. `attempt_timeout` bounds
    the whole attempt regardless. Returns (stdout_so_far, rc, init_hang,
    phases) where `phases` is the [{"phase", "t_s"}, ...] history the parent
    embeds in the BENCH JSON."""
    import threading

    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--run"],
        env=env, stdout=subprocess.PIPE, stderr=sys.stderr, text=True,
    )
    lines: list = []
    phases: list = []  # [(phase name, seconds since spawn)]
    ready = threading.Event()
    start = time.monotonic()
    progress = {"t": start, "phase": "spawned"}

    def _mark(phase: str) -> None:
        now = time.monotonic()
        progress["t"], progress["phase"] = now, phase
        phases.append({"phase": phase, "t_s": round(now - start, 3)})

    def reader():
        assert proc.stdout is not None
        for line in proc.stdout:
            lines.append(line)
            if line.startswith("@PHASE "):
                _mark(line[len("@PHASE "):].strip())
            elif line.startswith("@READY"):
                _mark("ready")
                ready.set()
            elif line.startswith("@RESULT"):
                # a finished lane is progress even if no phase line raced it
                progress["t"] = time.monotonic()
                ready.set()

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    hard_deadline = start + attempt_timeout
    killed = None
    init_hang = False
    while proc.poll() is None:
        now = time.monotonic()
        budget = READY_TIMEOUT_S if not ready.is_set() else PHASE_TIMEOUT_S
        if now - progress["t"] > budget:
            killed = (
                f"no progress past phase {progress['phase']!r} "
                f"(> {budget:.0f}s silent)"
            )
            # only a stall at (or before) backend-init is the tunnel-outage
            # signature the early-give-up counter tracks; a deadlocked lane
            # after @READY had a live backend and deserves a normal retry
            init_hang = progress["phase"] in ("spawned", "backend-init")
            break
        if now > hard_deadline:
            killed = f"attempt timeout ({attempt_timeout:.0f}s, phase {progress['phase']!r})"
            break
        time.sleep(1.0)
    if killed is not None:
        _log(f"bench child killed: {killed}")
        proc.kill()
        phases.append({"phase": f"killed:{killed}", "t_s": round(time.monotonic() - start, 3)})
    proc.wait()
    t.join(5.0)
    return "".join(lines), (proc.returncode if killed is None else -1), init_hang, phases


def emit(
    results: dict,
    telemetry_snap: Optional[dict] = None,
    attempts: Optional[list] = None,
    latency_lanes: Optional[dict] = None,
    ops_lanes: Optional[dict] = None,
) -> None:
    """The one stdout JSON line. Degrades to value 0.0 when nothing ran.
    The five headline BASELINES algos (pca/logreg/kmeans/kmeans_scale/knn)
    enter the geomean; extra lanes (sparse_logreg, cv_sweep, oocore_stream)
    are logged to stderr and still ride the record's "lanes" embed, which
    carries EVERY finite per-lane value for benchmark/regression.py's
    per-lane gates ("geomean_lanes" names the subset that formed the
    geomean — the gate's comparability key). When the child reported a
    telemetry snapshot (@TELEMETRY line), it is embedded under "telemetry"
    — the same counters/gauges/span-aggregate dict `telemetry.snapshot()`
    returns in-process (docs/observability.md). `attempts` is the
    per-attempt phase/watchdog history (which phases each child reached,
    what killed it) so a degraded emission explains ITSELF instead of
    requiring stderr archaeology."""
    for name, v in results.items():
        if name not in BASELINES and v and np.isfinite(v):
            _log(f"{name}: {v:,.0f} rows/sec/chip (no baseline; excluded from geomean)")
    ok = {k: v for k, v in results.items() if k in BASELINES and v and np.isfinite(v)}
    if ok:
        geo = float(np.exp(np.mean([np.log(v) for v in ok.values()])))
        geo_vs = float(np.exp(np.mean([np.log(ok[k] / BASELINES[k]) for k in ok])))
    else:
        geo, geo_vs = 0.0, 0.0
    missing = [a for a in ALGOS if a not in ok]
    unit = (
        f"rows/sec/chip (geomean of PCA k=3 / KMeans k=1000 / LogReg maxIter=200 / "
        f"their solver_precision=bf16 lanes / "
        f"KMeans-scale 1-pass k=1000 / kNN q={KNN_QUERIES} k={KNN_K} / "
        f"Serving {SERVE_REQUESTS}req k={SERVE_K} "
        f"on {N_ROWS // 1000}k x {N_COLS}, f32"
        + (f"; INCOMPLETE, missing {'+'.join(missing)}" if missing else "")
        + ")"
    )
    for name, v in ok.items():
        _log(f"{name}: {v:,.0f} rows/sec/chip (baseline {BASELINES[name]:,.0f}; {v / BASELINES[name]:.1f}x)")
    record = {
        "metric": "classical_ml_fit_throughput_geomean",
        "value": round(geo, 1),
        "unit": unit,
        "vs_baseline": round(geo_vs, 3),
        # per-lane values (baseline lanes AND extras): benchmark/regression.py
        # gates each lane against ITS OWN trajectory — the first artifact
        # carrying a lane starts that lane's history instead of false-failing
        # against rounds that predate it
        "lanes": {
            name: round(v, 1)
            for name, v in results.items()
            if v and np.isfinite(v)
        },
        # which of those lanes entered the headline geomean: the regression
        # gate keys geomean COMPARABILITY on this set, so toggling an
        # optional extra lane (BENCH_SPARSE/BENCH_OOCORE) cannot silently
        # skip the headline gate
        "geomean_lanes": sorted(ok),
    }
    if latency_lanes:
        # p50/p99 serving latencies: benchmark/regression.py gates each as a
        # LOWER-IS-BETTER lane against its own trajectory, so a p99 blowup
        # fails even when the throughput lanes look fine
        record["latency_lanes"] = {k: float(v) for k, v in latency_lanes.items()}
    if ops_lanes:
        # per-lane ops embeds (end-of-run SLO verdict + per-tenant
        # byte-seconds): REPORT-ONLY — the regression gate never reads them
        record["ops"] = ops_lanes
    if telemetry_snap:
        record["telemetry"] = telemetry_snap
    if attempts:
        record["attempts"] = attempts
    print(json.dumps(record), flush=True)


def main() -> None:
    results: dict = {}
    telemetry_snap: dict = {}
    attempts: list = []
    latency_lanes: dict = {}
    ops_lanes: dict = {}
    try:
        _attempt_loop(results, telemetry_snap, attempts, latency_lanes, ops_lanes)
    except Exception as e:  # the JSON line is a CONTRACT: never die before emit
        _log(f"bench driver error: {type(e).__name__}: {e}")
    emit(results, telemetry_snap, attempts, latency_lanes, ops_lanes)


def _attempt_loop(
    results: dict,
    telemetry_snap: Optional[dict] = None,
    attempts: Optional[list] = None,
    latency_lanes: Optional[dict] = None,
    ops_lanes: Optional[dict] = None,
) -> None:
    # total budget DEFAULTS BELOW any plausible driver timeout: if the caller
    # kills this process before emit(), the JSON contract is lost — 45 min
    # fits ~4 full attempts at the protocol scale with backoff. A run of
    # consecutive init-hang kills (the tunnel never answered once) ends the
    # loop even earlier: sustained outage, emit the degraded JSON while the
    # caller is still listening.
    deadline = time.monotonic() + float(os.environ.get("BENCH_TOTAL_TIMEOUT", 2700))
    max_init_hangs = int(os.environ.get("BENCH_MAX_INIT_HANGS", 3))
    init_hangs = 0
    for attempt in range(1, MAX_ATTEMPTS + 1):
        pending = [a for a in bench_algos() if a not in results]
        if not pending:
            break
        if time.monotonic() > deadline:
            _log("bench: total time budget exhausted")
            break
        env = dict(os.environ, BENCH_SKIP=",".join(a for a in bench_algos() if a in results))
        _log(f"bench attempt {attempt}/{MAX_ATTEMPTS}: running {'+'.join(pending)}")
        t0 = time.monotonic()
        out, rc, init_hang, phases = _run_child_watched(
            env,
            attempt_timeout=min(ATTEMPT_TIMEOUT_S, max(60.0, deadline - time.monotonic())),
        )
        for line in out.splitlines():
            if line.startswith("@RESULT "):
                try:
                    rec = json.loads(line[len("@RESULT "):])
                    results[rec["algo"]] = float(rec["rows_per_sec_chip"])
                    if latency_lanes is not None and isinstance(rec.get("latency"), dict):
                        latency_lanes.update(
                            {k: float(v) for k, v in rec["latency"].items()}
                        )
                    if ops_lanes is not None and isinstance(rec.get("ops"), dict):
                        ops_lanes[rec["algo"]] = rec["ops"]
                except (ValueError, KeyError, TypeError):
                    pass
            elif line.startswith("@TELEMETRY ") and telemetry_snap is not None:
                try:  # last reporting child wins (one snapshot per attempt)
                    snap = json.loads(line[len("@TELEMETRY "):])
                    if isinstance(snap, dict):
                        telemetry_snap.clear()
                        telemetry_snap.update(snap)
                except ValueError:
                    pass
        if attempts is not None:
            attempts.append({
                "attempt": attempt,
                "rc": rc,
                "elapsed_s": round(time.monotonic() - t0, 1),
                "ran": pending,
                "phases": phases,
            })
        if all(a in results for a in bench_algos()):
            break
        elapsed = time.monotonic() - t0
        _log(f"bench attempt {attempt}: rc={rc}, have {sorted(results)} after {elapsed:.0f}s")
        init_hangs = init_hangs + 1 if init_hang else 0
        if init_hangs >= max_init_hangs:
            _log(
                f"bench: {init_hangs} consecutive backend-init hangs — "
                "sustained accelerator outage, giving up early"
            )
            break
        if attempt < MAX_ATTEMPTS:
            pause = BACKOFF_FAST_FAIL_S if elapsed < FAST_FAIL_WINDOW_S else BACKOFF_SLOW_FAIL_S
            pause = min(pause, max(0.0, deadline - time.monotonic()))
            if pause:
                _log(f"bench: backing off {pause:.0f}s before retry")
                time.sleep(pause)


if __name__ == "__main__":
    if "--run" in sys.argv[1:]:
        sys.exit(run_child())
    main()
