#
# Round benchmark: the reference protocol's three headline fit configs
# (BASELINE.md — PCA k=3, KMeans k=1000 maxIter=30, LogisticRegression
# maxIter=200 reg=1e-5, all on the 1M x 3k suite shape) scaled to one chip's
# HBM, run on the real TPU.
#
# Prints ONE JSON line on stdout:
#   {"metric", "value", "unit", "vs_baseline"}
# value = geometric mean of fit throughput (rows/sec/chip) across the three
# algos; per-algo detail goes to stderr.
#
# Baseline normalization: the reference publishes a protocol + bar chart, no
# numbers (SURVEY.md §6). We normalize against A100-class per-algo assumptions
# on the 1M x 3k configs (2 workers): PCA 10 s, KMeans 60 s, LogReg 40 s
# => per-chip baselines {pca: 50k, kmeans: 8.3k, logreg: 12.5k} rows/sec/chip.
# vs_baseline = geomean(measured/baseline) — >1 beats the A100-class estimate.
#
from __future__ import annotations

import json
import sys
import time

import numpy as np

N_ROWS = 400_000  # 1M x 3k f32 is ~12 GB; 400k keeps everything + workspace in HBM
N_COLS = 3000
BASELINES = {"pca": 50_000.0, "kmeans": 8_333.0, "logreg": 12_500.0}


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _time_fit(run, fetch, repeats=2) -> float:
    """Wall-clock with forced device->host fetch (block_until_ready is not
    reliable on the experimental axon PJRT platform)."""
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = run()
        np.asarray(fetch(out))
        best = min(best, time.perf_counter() - t0)
    return best


def bench_pca(X, w, mesh) -> float:
    import jax

    from spark_rapids_ml_tpu.ops.pca import pca_fit

    fit = jax.jit(lambda X, w: pca_fit(X, w, k=3))
    np.asarray(fit(X, w)["components_"])  # compile + warm
    fit_s = _time_fit(lambda: fit(X, w), lambda s: s["components_"])
    _log(f"pca: {fit_s:.2f}s fit")
    return N_ROWS / fit_s


def bench_kmeans(X, w, mesh) -> float:
    import jax

    from spark_rapids_ml_tpu.ops.kmeans import kmeans_fit

    k = 1000
    # random-row init picked on device (initMode=random in the protocol config)
    idx = jax.random.choice(jax.random.PRNGKey(1), X.shape[0], (k,), replace=False)
    centers0 = jax.device_put(np.asarray(X[idx]))  # replicated
    run = lambda: kmeans_fit(  # noqa: E731
        X, w, centers0, mesh=mesh, max_iter=30, tol=1e-20, batch_rows=16384
    )
    np.asarray(run()["cluster_centers_"])  # compile + warm
    fit_s = _time_fit(lambda: run(), lambda s: s["cluster_centers_"], repeats=1)
    _log(f"kmeans: {fit_s:.2f}s fit (k={k}, maxIter=30)")
    return N_ROWS / fit_s


def bench_logreg(X, w, y_idx) -> float:
    from spark_rapids_ml_tpu.ops.logistic import logistic_fit

    run = lambda: logistic_fit(  # noqa: E731
        X, y_idx, w, k=2, multinomial=False, lam_l2=1e-5,
        fit_intercept=True, standardize=True, max_iter=200, tol=1e-30,
    )
    np.asarray(run()["coef_"])  # compile + warm
    fit_s = _time_fit(lambda: run(), lambda s: s["coef_"], repeats=1)
    _log(f"logreg: {fit_s:.2f}s fit (maxIter=200, tol=1e-30)")
    return N_ROWS / fit_s


def main() -> None:
    import jax
    import jax.numpy as jnp

    from spark_rapids_ml_tpu.parallel import get_mesh, row_sharding

    mesh = get_mesh()
    n_chips = int(mesh.devices.size)
    t0 = time.perf_counter()
    _log(f"generating {N_ROWS}x{N_COLS} dataset ON DEVICE...")

    # generate the low-rank + noise dataset on device (no host transfer): the
    # reference's PCA/regression dataset shape (gen_data.py low_rank_matrix)
    @jax.jit
    def gen(key):
        k1, k2, k3, k4, k5 = jax.random.split(key, 5)
        rank = 16
        U = jax.random.normal(k1, (N_ROWS, rank), jnp.float32)
        V = jax.random.normal(k2, (rank, N_COLS), jnp.float32)
        X = U @ V + 0.1 * jax.random.normal(k3, (N_ROWS, N_COLS), jnp.float32)
        coef = jax.random.normal(k4, (N_COLS,), jnp.float32) / np.sqrt(N_COLS)
        margin = X @ coef
        y = (margin + 0.5 * jax.random.normal(k5, (N_ROWS,), jnp.float32) > 0).astype(jnp.int32)
        w = jnp.ones((N_ROWS,), jnp.float32)
        return X, y, w

    shardings = (row_sharding(mesh, 2), row_sharding(mesh, 1), row_sharding(mesh, 1))
    X, y_idx, w = jax.jit(gen, out_shardings=shardings)(jax.random.PRNGKey(0))
    np.asarray(w[:1])  # force materialization for honest phase timing
    _log(f"datagen: {time.perf_counter() - t0:.1f}s")

    results = {}
    results["pca"] = bench_pca(X, w, mesh) / n_chips
    results["logreg"] = bench_logreg(X, w, y_idx) / n_chips
    results["kmeans"] = bench_kmeans(X, w, mesh) / n_chips

    for name, v in results.items():
        _log(f"{name}: {v:,.0f} rows/sec/chip (baseline {BASELINES[name]:,.0f}; {v / BASELINES[name]:.1f}x)")
    geo = float(np.exp(np.mean([np.log(v) for v in results.values()])))
    geo_vs = float(np.exp(np.mean([np.log(results[k] / BASELINES[k]) for k in results])))
    print(
        json.dumps(
            {
                "metric": "classical_ml_fit_throughput_geomean",
                "value": round(geo, 1),
                "unit": "rows/sec/chip (geomean of PCA k=3 / KMeans k=1000 / LogReg maxIter=200 on 3000 cols, f32)",
                "vs_baseline": round(geo_vs, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
