#
# Round benchmark: the reference protocol's three headline fit configs
# (BASELINE.md — PCA k=3, KMeans k=1000 maxIter=30, LogisticRegression
# maxIter=200 reg=1e-5) at the TRUE protocol scale 1M x 3k, on the real TPU.
#
# Prints ONE JSON line on stdout:
#   {"metric", "value", "unit", "vs_baseline"}
# value = geometric mean of fit throughput (rows/sec/chip) across the three
# algos; per-algo detail goes to stderr. The full 10-config suite lives in
# benchmark/ (python -m benchmark.benchmark_runner protocol).
#
# Memory: X is 1M x 3000 f32 = 11.2 GiB, generated tile-wise DIRECTLY into a
# row-sharded HBM buffer (benchmark/gen_data.py) — peak = X + one 64k-row tile,
# inside a single v5e chip's 16 GB.
#
# Baseline normalization: the reference publishes a protocol + bar chart, no
# numbers (SURVEY.md §6). We normalize against A100-class per-algo assumptions
# on the 1M x 3k configs (2 workers): PCA 10 s, KMeans 60 s, LogReg 40 s
# => per-chip baselines {pca: 50k, kmeans: 8.3k, logreg: 12.5k} rows/sec/chip.
# vs_baseline = geomean(measured/baseline) — >1 beats the A100-class estimate.
#
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

N_ROWS = int(os.environ.get("BENCH_ROWS", 1_000_000))
N_COLS = int(os.environ.get("BENCH_COLS", 3000))
BASELINES = {"pca": 50_000.0, "kmeans": 8_333.0, "logreg": 12_500.0}


def _log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def _time_fit(run, fetch, repeats=2) -> float:
    """Wall-clock with forced device->host fetch (block_until_ready is not
    reliable on the experimental axon PJRT platform)."""
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = run()
        np.asarray(fetch(out))
        best = min(best, time.perf_counter() - t0)
    return best


def bench_pca(X, w, mesh) -> float:
    import jax

    from spark_rapids_ml_tpu.ops.pca import pca_fit

    fit = jax.jit(lambda X, w: pca_fit(X, w, k=3))
    np.asarray(fit(X, w)["components_"])  # compile + warm
    fit_s = _time_fit(lambda: fit(X, w), lambda s: s["components_"])
    _log(f"pca: {fit_s:.2f}s fit")
    return N_ROWS / fit_s


def bench_kmeans(X, w, mesh) -> float:
    import jax

    from spark_rapids_ml_tpu.ops.kmeans import kmeans_fit

    k = 1000
    # random-row init (initMode=random protocol config). The rows are iid, so
    # ONE contiguous k-row block at a random offset is an equally random
    # sample: a single dynamic_slice program (per-row pulls cost ~145 s of
    # dispatch latency through the tunnel; a fancy-index gather program on the
    # 11 GiB X makes XLA materialize a full copy — measured OOM).
    rng = np.random.default_rng(1)
    r0 = int(rng.integers(0, max(1, X.shape[0] - k + 1)))
    centers0 = jax.jit(lambda X: jax.lax.dynamic_slice_in_dim(X, r0, k, 0))(X)
    np.asarray(centers0[:1])

    def run():
        # KMeans precision policy: 3-pass bf16 MXU (parallel/mesh.py dtype_scope)
        with jax.default_matmul_precision("BF16_BF16_F32_X3"):
            return kmeans_fit(
                X, w, centers0, mesh=mesh, max_iter=30, tol=1e-20, batch_rows=65536
            )

    np.asarray(run()["cluster_centers_"])  # compile + warm
    fit_s = _time_fit(run, lambda s: s["cluster_centers_"], repeats=1)
    _log(f"kmeans: {fit_s:.2f}s fit (k={k}, maxIter=30)")
    return N_ROWS / fit_s


def bench_logreg(X, w, y_idx) -> float:
    from spark_rapids_ml_tpu.ops.logistic import logistic_fit

    run = lambda: logistic_fit(  # noqa: E731
        X, y_idx, w, k=2, multinomial=False, lam_l2=1e-5,
        fit_intercept=True, standardize=True, max_iter=200, tol=1e-30,
    )
    np.asarray(run()["coef_"])  # compile + warm
    fit_s = _time_fit(lambda: run(), lambda s: s["coef_"], repeats=1)
    _log(f"logreg: {fit_s:.2f}s fit (maxIter=200, tol=1e-30)")
    return N_ROWS / fit_s


def main() -> None:
    import jax

    from benchmark.gen_data import gen_classification_device
    from spark_rapids_ml_tpu.parallel import get_mesh

    mesh = get_mesh()
    n_chips = int(mesh.devices.size)
    t0 = time.perf_counter()
    _log(f"generating {N_ROWS}x{N_COLS} dataset tile-wise ON DEVICE...")
    # single chip: plain (uncommitted-sharding) arrays — a committed
    # NamedSharding makes Shardy insert a full input-resharding copy of X in
    # downstream programs (11 GiB here), while GSPMD on a 1-device mesh needs
    # no sharding annotations at all
    X, y_idx, w = gen_classification_device(
        N_ROWS, N_COLS, n_classes=2, mesh=mesh if n_chips > 1 else None
    )
    np.asarray(w[:1])  # force materialization for honest phase timing
    _log(f"datagen: {time.perf_counter() - t0:.1f}s")

    results = {}
    results["pca"] = bench_pca(X, w, mesh) / n_chips
    results["logreg"] = bench_logreg(X, w, y_idx) / n_chips
    results["kmeans"] = bench_kmeans(X, w, mesh) / n_chips

    for name, v in results.items():
        _log(f"{name}: {v:,.0f} rows/sec/chip (baseline {BASELINES[name]:,.0f}; {v / BASELINES[name]:.1f}x)")
    geo = float(np.exp(np.mean([np.log(v) for v in results.values()])))
    geo_vs = float(np.exp(np.mean([np.log(results[k] / BASELINES[k]) for k in results])))
    print(
        json.dumps(
            {
                "metric": "classical_ml_fit_throughput_geomean",
                "value": round(geo, 1),
                "unit": "rows/sec/chip (geomean of PCA k=3 / KMeans k=1000 / LogReg maxIter=200 on 1M x 3000, f32)",
                "vs_baseline": round(geo_vs, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
