#
# Round benchmark: runs the headline fit configs from the reference's protocol
# (BASELINE.md: PCA k=3 on the 1M x 3k suite shape) on the real TPU chip and
# prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
#
# Baseline normalization: the reference publishes no numbers (SURVEY.md §6) —
# its protocol ran 2x A10G with fit wall-clocks "inside the 3600 s limit" and a
# bar chart of tens-of-seconds fits. We normalize against an A100-class
# assumption of a 10 s PCA fit on 1M x 3k with 2 workers => 50_000 rows/sec/chip;
# vs_baseline = measured_rows_per_sec_per_chip / 50_000.
#
from __future__ import annotations

import json
import time

import numpy as np


def _bench_pca(n_rows: int, n_cols: int, k: int = 3, repeats: int = 3) -> float:
    import jax

    from spark_rapids_ml_tpu.ops.pca import pca_fit
    from spark_rapids_ml_tpu.parallel import get_mesh, make_global_rows

    mesh = get_mesh()  # all visible chips (1 on the bench runner)
    n_chips = int(mesh.devices.size)
    rng = np.random.default_rng(0)
    # low-rank + noise matrix like the reference's PCA dataset (gen_data.py)
    d_rank = 16
    X_host = (
        rng.normal(size=(n_rows, d_rank)).astype(np.float32)
        @ rng.normal(size=(d_rank, n_cols)).astype(np.float32)
        + 0.1 * rng.normal(size=(n_rows, n_cols)).astype(np.float32)
    )
    X, w, _ = make_global_rows(mesh, X_host)

    fit = jax.jit(lambda X, w: pca_fit(X, w, k=k))

    def run_once() -> float:
        t0 = time.perf_counter()
        state = fit(X, w)
        # force full execution with a device->host fetch (block_until_ready is
        # not reliable on the experimental axon PJRT platform)
        _ = np.asarray(state["components_"])
        return time.perf_counter() - t0

    run_once()  # compile + warm
    fit_s = min(run_once() for _ in range(repeats))
    return n_rows / fit_s / n_chips


def main() -> None:
    # Suite shape scaled to fit one chip's HBM alongside workspace (the full
    # 1M x 3k f32 block is ~12 GB; 400k x 3k ~ 4.8 GB leaves headroom).
    rows_per_sec_chip = _bench_pca(400_000, 3000)
    baseline = 50_000.0
    print(
        json.dumps(
            {
                "metric": "pca_fit_throughput",
                "value": round(rows_per_sec_chip, 1),
                "unit": "rows/sec/chip (PCA k=3, 3000 cols, f32)",
                "vs_baseline": round(rows_per_sec_chip / baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
